"""Deterministic fault-injection harness (chaos layer) for recovery paths.

The checkpoint/resume, collective-deadline, and bench-retry machinery all
exist to survive failures that are rare and non-deterministic in the wild:
a preempted TPU worker (the BENCH_r05 death), a pod barrier that never
returns, a snapshot half-written when the VM disappears. This module makes
every one of those failures *injectable on demand*, so each recovery path
is exercised deterministically in tier-1 instead of trusted.

Faults are described by a compact spec string, driven by the
``LGBM_TPU_FAULTS`` environment variable or the ``tpu_fault_spec`` config
parameter::

    kill@iteration=3                 raise SimulatedKill before iteration 3
    hang@step=2:seconds=60           sleep 60s inside the watchdog-wrapped
                                     training step of iteration 2
    transient@backend_init=1:count=2 fail the first two backend-init
                                     attempts with a transient error
    transient@bench_update=7         fail bench's 7th update transiently
    corrupt@snapshot=2               corrupt the 2nd snapshot file written
    corrupt@snapshot=2:mode=flip     ... by flipping payload bytes instead
                                     of truncating

Multiple faults join with ``;``. Each fault fires ``count`` times
(default 1) and then disarms, so "transient failure then recovery" is a
single spec. Sites fired by the production code:

======================  =====================================================
``iteration``           engine.train, before each boosting iteration
                        (``iteration=`` matches the 0-based loop index)
``step``                inside the collective-deadline watchdog, just before
                        ``booster.update()`` (``iteration=`` 0-based)
``barrier``             parallel/mesh.py sync_barrier (ordinal, 1-based)
``backend_init``        bench.py backend init/enumeration attempts and
                        parallel/multihost.py bootstrap (ordinal, 1-based)
``snapshot``            io/checkpoint.py after a snapshot file lands
                        (ordinal, 1-based; ``corrupt`` rewrites the file)
``bench_update``        bench.py resumable update loop, before each update
                        (``iteration=`` 1-based absolute iteration)
``request``             serving/server.py submit, before admission control
                        (ordinal, 1-based)
``coalesce_tick``       serving/coalescer.py, after a batch is popped and
                        before it is served (ordinal, 1-based; ``hang`` =
                        a slow tick, ``kill`` = a dead serving worker)
``warmup``              Booster.warm_predict_ladder, before each ladder
                        rung is compiled (ordinal, 1-based)
``swap``                serving/registry.py, inside the deadline-guarded
                        hot-swap commit, before the active-model flip
                        (ordinal, 1-based; a ``hang`` past the swap
                        deadline must roll back)
======================  =====================================================

Injection sites call :func:`active_plan` and ``fire()`` — a no-op
``NullPlan`` when no spec is set, so the hot paths pay one attribute call.
Tests install plans explicitly with :func:`inject` (a context manager)
instead of mutating the environment.
"""
from __future__ import annotations

import contextlib
import dataclasses
import os
import time
from typing import Dict, List, Optional

from ..utils import log

#: the message transient-fault injections carry — matches the
#: bench/bootstrap transient-error classifiers by substring
TRANSIENT_MESSAGE = "Unable to initialize backend (injected transient fault)"


class SimulatedKill(BaseException):
    """An injected ``kill -9``: escapes every ``except Exception`` handler
    (it subclasses BaseException) so NO cleanup-path snapshot is written —
    recovery must come from the last periodic snapshot, exactly like a
    real preemption."""


class FaultSpecError(ValueError):
    """Malformed LGBM_TPU_FAULTS / tpu_fault_spec string."""


_KINDS = ("kill", "hang", "transient", "corrupt")
_SITES = ("iteration", "step", "barrier", "backend_init", "snapshot",
          "bench_update", "request", "coalesce_tick", "warmup", "swap")


@dataclasses.dataclass
class Fault:
    kind: str                    # kill | hang | transient | corrupt
    site: str                    # one of _SITES
    at: int                      # iteration/ordinal to START firing at
    #                              (fires while count remains); -1 = always
    count: int = 1               # fires before disarming; -1 = unlimited
    seconds: float = 3600.0      # hang sleep
    mode: str = "truncate"       # corrupt: truncate | flip
    fired: int = 0

    def spent(self) -> bool:
        return self.count >= 0 and self.fired >= self.count


def parse_spec(spec: str) -> List[Fault]:
    """Parse ``kind@site=at[:key=val...]`` clauses joined by ``;``."""
    faults: List[Fault] = []
    for clause in (c.strip() for c in spec.split(";")):
        if not clause:
            continue
        if "@" not in clause:
            raise FaultSpecError(
                f"fault clause {clause!r} needs kind@site=at")
        kind, _, rest = clause.partition("@")
        kind = kind.strip().lower()
        if kind not in _KINDS:
            raise FaultSpecError(
                f"unknown fault kind {kind!r} (one of {_KINDS})")
        parts = rest.split(":")
        site, _, at_s = parts[0].partition("=")
        site = site.strip().lower()
        if site not in _SITES:
            raise FaultSpecError(
                f"unknown fault site {site!r} (one of {_SITES})")
        try:
            at = int(at_s) if at_s.strip() not in ("", "*") else -1
        except ValueError:
            raise FaultSpecError(
                f"fault clause {clause!r}: non-integer position {at_s!r}")
        fault = Fault(kind=kind, site=site, at=at)
        for extra in parts[1:]:
            key, _, val = extra.partition("=")
            key = key.strip().lower()
            if key == "count":
                fault.count = int(val)
            elif key == "seconds":
                fault.seconds = float(val)
            elif key == "mode":
                if val not in ("truncate", "flip"):
                    raise FaultSpecError(
                        f"corrupt mode {val!r} (truncate|flip)")
                fault.mode = val
            else:
                raise FaultSpecError(
                    f"unknown fault option {key!r} in {clause!r}")
        faults.append(fault)
    return faults


def corrupt_file(path: str, mode: str = "truncate") -> None:
    """Damage a snapshot file in place (simulates a torn write that an
    atomic rename would normally prevent — e.g. direct disk corruption)."""
    size = os.path.getsize(path)
    if mode == "truncate":
        with open(path, "r+b") as fh:
            fh.truncate(max(size // 2, 1))
    else:  # flip payload bytes mid-file
        with open(path, "r+b") as fh:
            fh.seek(max(size // 2, 0))
            chunk = fh.read(8)
            fh.seek(max(size // 2, 0))
            fh.write(bytes(b ^ 0xFF for b in chunk))


class FaultPlan:
    """A parsed fault set plus per-site fire ordinals."""

    def __init__(self, faults: List[Fault]):
        self.faults = faults
        self._site_ordinal: Dict[str, int] = {}

    def fire(self, site: str, **ctx) -> None:
        """Trigger any armed fault matching ``site`` at this position.

        Sites that pass ``iteration=`` match on it; others match on the
        1-based per-site fire ordinal. ``at`` is the FIRST position a
        fault fires at; it keeps firing at subsequent positions while
        ``count`` remains (so ``transient@backend_init=1:count=2`` fails
        the first two attempts, as documented)."""
        ordinal = self._site_ordinal.get(site, 0) + 1
        self._site_ordinal[site] = ordinal
        position = ctx.get("iteration", ordinal)
        for f in self.faults:
            if f.site != site or f.spent():
                continue
            if f.at >= 0 and position < f.at:
                continue
            f.fired += 1
            self._trigger(f, ctx)

    def _trigger(self, f: Fault, ctx: dict) -> None:
        where = f"{f.site}@{ctx.get('iteration', self._site_ordinal[f.site])}"
        # black-box entry BEFORE the fault acts: a kill escapes every
        # handler, but the ring (dumped by the crash/interrupt handlers,
        # or at the next checkpoint tick) names the site that fired
        from ..obs import flight
        flight.note("fault_fire", site=f.site, kind=f.kind, at=where,
                    fired=f.fired)
        if f.kind == "kill":
            log.warning(f"[faultinject] simulated kill at {where}")
            raise SimulatedKill(f"injected kill at {where}")
        if f.kind == "hang":
            log.warning(f"[faultinject] injected hang at {where} "
                        f"({f.seconds:.0f}s)")
            time.sleep(f.seconds)
            return
        if f.kind == "transient":
            log.warning(f"[faultinject] injected transient failure at "
                        f"{where}")
            raise RuntimeError(TRANSIENT_MESSAGE)
        if f.kind == "corrupt":
            path = ctx.get("path")
            if path and os.path.exists(path):
                log.warning(f"[faultinject] corrupting snapshot {path} "
                            f"({f.mode})")
                corrupt_file(path, f.mode)


class NullPlan:
    """Armed when no spec is set: fire() is a no-op."""

    faults: List[Fault] = []

    def fire(self, site: str, **ctx) -> None:
        return None


_NULL = NullPlan()
_installed: Optional[FaultPlan] = None
_env_plan: Optional[FaultPlan] = None
_env_spec: Optional[str] = None
_config_plan: Optional[FaultPlan] = None
_config_spec: Optional[str] = None


def install(plan: Optional[FaultPlan]) -> None:
    """Install (or clear, with None) an explicit plan — wins over env."""
    global _installed
    _installed = plan


@contextlib.contextmanager
def inject(spec: str):
    """Context manager: arm ``spec`` for the block, restore after.

    Yields the plan so tests can assert ``fired`` counters."""
    plan = FaultPlan(parse_spec(spec))
    prev = _installed
    install(plan)
    try:
        yield plan
    finally:
        install(prev)


def active_plan(config=None):
    """The currently armed plan: explicit install > LGBM_TPU_FAULTS env >
    config ``tpu_fault_spec`` > NullPlan.

    Env- and config-driven plans are built once per distinct spec value
    and keep their fire counters for the life of the process (a
    ``count=1`` fault fires once per process, like a real one-off
    failure would). A config-armed plan is STICKY: once a config carrying
    ``tpu_fault_spec`` has been seen (engine.train setup), the plan also
    serves the sites that have no config in hand (snapshot writes,
    barriers, bench hooks); a later config with an empty spec disarms it."""
    global _env_plan, _env_spec, _config_plan, _config_spec
    if _installed is not None:
        return _installed
    spec = os.environ.get("LGBM_TPU_FAULTS", "")
    if spec:
        if spec != _env_spec:
            _env_plan = FaultPlan(parse_spec(spec))
            _env_spec = spec
        return _env_plan
    if config is not None:
        try:
            cspec = str(config.get("tpu_fault_spec", "") or "")
        except Exception:
            cspec = ""
        if cspec != _config_spec:
            _config_plan = FaultPlan(parse_spec(cspec)) if cspec else None
            _config_spec = cspec
    return _config_plan if _config_plan is not None else _NULL
