"""GBDT training driver.

TPU-native re-design of the reference's boosting layer
(reference: GBDT, src/boosting/gbdt.cpp — Init :53, TrainOneIter :344-452,
Boosting [gradient compute] :220, UpdateScore :491, RollbackOneIter :454,
BoostFromAverage :319; ScoreUpdater src/boosting/score_updater.hpp:21 and its
CUDA variant src/boosting/cuda/cuda_score_updater.cu).

Layout decisions (vs the reference):
  * scores are a device-resident ``[K, N]`` array (K = trees per iteration,
    i.e. num_class for multiclass) — the reference keeps a flat K*N buffer;
  * gradients/hessians never leave HBM between the objective kernel and the
    histogram contraction (same contract as the CUDA path, §3.3 of SURVEY);
  * the in-bag mask is a dense {0,1} vector multiplied into grad/hess/count
    channels instead of compacted ``bag_data_indices`` (static shapes);
  * trees are stored as host numpy struct-of-arrays (models are tiny) and
    re-stacked to device arrays for batch prediction.
"""
from __future__ import annotations

import functools
import os
import threading
import weakref

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..io.dataset import BinnedDataset
from ..metrics import Metric
from ..objectives import Objective
from ..ops.compact import RowLayout, pack_rows, segments_to_leaf_vectors
from ..ops.grower import (GrowerParams, TreeArrays, depth_rung, grow_tree,
                          leaf_rung)
from ..ops.grower_compact import grow_tree_compact
from ..ops.predict import (DEFAULT_LEVEL_DEPTH_CAP, StackedTrees,
                           bucket_rows, build_level_layout, depth_bucket,
                           early_stop_tbatch, parse_bucket_ladder,
                           predict_leaf_batched, predict_leaf_level,
                           predict_raw_batched, predict_raw_level,
                           predict_raw_scan, quantize_leaves,
                           route_one_tree, tree_bucket)
from ..parallel.multihost import to_host as _to_host
from ..ops.renew import renew_leaf_quantile
from ..utils import log
from ..utils.rwlock import Mutex
from .sample_strategy import GOSSStrategy, create_sample_strategy

_EPS = 1e-35

#: boosters whose UNWIND-table cache is live — probed (entry count) by the
#: resource witness; WeakSet so a dropped booster stops being counted
_shap_table_boosters: "weakref.WeakSet" = weakref.WeakSet()
_shap_probe_lock = threading.Lock()
_shap_probe_registered = False


def _register_shap_table_probe(booster) -> None:
    """R012 hook: the UNWIND-table cache is a keyed retained-data cache,
    so its live entry count feeds ``guards.resource_witness``'s
    jit_cache delta (one module-level probe, registered on first use)."""
    global _shap_probe_registered
    with _shap_probe_lock:
        _shap_table_boosters.add(booster)
        if _shap_probe_registered:
            return
        from ..analysis import guards
        guards.register_witness_cache_probe(
            lambda: sum(len(getattr(b, "_shap_tables_cache", None) or {})
                        for b in list(_shap_table_boosters)))
        _shap_probe_registered = True


def _bound_gradients(obj, k_total: int, scores, label, weight):
    """Objective gradients with label/weight rebound to the compact grower's
    current row order (the objective's stored arrays are in the original
    order; see Objective.row_elementwise)."""
    from ..obs.spans import span
    old_l, old_w = obj.label, obj.weight
    obj.label, obj.weight = label, weight
    try:
        with span("gradient"):
            if k_total == 1:
                g, h = obj.get_gradients(scores[0])
                return g[None, :], h[None, :]
            return obj.get_gradients(scores)
    finally:
        obj.label, obj.weight = old_l, old_w


def _parse_monotone(value, num_features: int, feature_names) -> Optional[np.ndarray]:
    """monotone_constraints -> [F] int8 (list, comma string, or name dict)."""
    if value is None:
        return None
    if isinstance(value, str):
        value = [int(v) for v in value.replace("(", "").replace(")", "")
                 .split(",") if v.strip()]
    if isinstance(value, dict):
        out = np.zeros(num_features, np.int8)
        for name, v in value.items():
            out[list(feature_names).index(name)] = int(v)
        return out if out.any() else None
    arr = np.asarray(list(value), np.int8)
    if arr.size != num_features:
        raise ValueError(
            f"monotone_constraints has {arr.size} entries for "
            f"{num_features} features")
    return arr if arr.any() else None


def _parse_interactions(value, num_features: int) -> Optional[np.ndarray]:
    """interaction_constraints -> [S, F] bool masks (list of index lists or
    the reference's "[0,1],[2,3]" string form)."""
    if value in (None, "", []):
        return None
    if isinstance(value, str):
        import json as _json
        value = _json.loads("[" + value + "]")
    sets = np.zeros((len(value), num_features), bool)
    for i, group in enumerate(value):
        sets[i, np.asarray(list(group), np.int64)] = True
    return sets


def _discretize_gradients(grad, hess, key, num_bins: int, stochastic: bool,
                          const_hess: bool, axis_name=None):
    """Gradient discretization (reference:
    GradientDiscretizer::DiscretizeGradients, gradient_discretizer.cpp):
    gradients snap to num_grad_quant_bins levels of max|g|/(bins/2) with
    stochastic rounding. Returns ``(qg, qh, g_scale, h_scale)`` — the CODE
    arrays (integer-valued f32: |qg| <= bins/2, 0 <= qh <= bins, so they
    cast exactly to int8 for bins <= 127) plus the per-iteration scales.
    The int-histogram pipeline consumes the codes directly; the masked
    grower's shim multiplies them back (``_quantize_gradients``).

    ``axis_name``: under shard_map the max-abs scale must be GLOBAL (pmax)
    — per-shard scales would make the psum-ed int histograms sum codes on
    different grids."""
    gmax = jnp.max(jnp.abs(grad))
    hmax = jnp.max(jnp.abs(hess))
    if axis_name is not None:
        gmax = jax.lax.pmax(gmax, axis_name)
        hmax = jax.lax.pmax(hmax, axis_name)
    g_scale = jnp.maximum(gmax / (num_bins // 2), 1e-30)
    h_scale = jnp.maximum(
        hmax if const_hess else hmax / num_bins, 1e-30)
    if stochastic:
        kg, kh = jax.random.split(key)
        ug = jax.random.uniform(kg, grad.shape)
        uh = jax.random.uniform(kh, hess.shape)
        qg = jnp.trunc(grad / g_scale + jnp.sign(grad) * ug)
        qh = jnp.trunc(hess / h_scale + uh)
    else:
        qg = jnp.trunc(grad / g_scale + jnp.sign(grad) * 0.5)
        qh = jnp.trunc(hess / h_scale + 0.5)
    return qg, qh, g_scale, h_scale


def _quantize_gradients(grad, hess, key, num_bins: int, stochastic: bool,
                        const_hess: bool):
    """Dequantized-f32 shim over ``_discretize_gradients`` for the masked
    grower: codes multiply straight back by their scales (exact integer
    multiples), so that histogram pipeline is unchanged while the training
    statistics match the reference's coarse-gradient regularization. The
    compact grower skips this shim and feeds the codes to the int8 MXU
    histogram path instead (ops/grower_compact.py quant_hist)."""
    qg, qh, g_scale, h_scale = _discretize_gradients(
        grad, hess, key, num_bins, stochastic, const_hess)
    return qg * g_scale, qh * h_scale


def _tree_used_features(tree, nf: int, used: jax.Array) -> jax.Array:
    """OR the tree's split features into the model-level CEGB used set."""
    idx = jnp.where(tree.split_feature >= 0, tree.split_feature, nf)
    return used | jnp.zeros((nf + 1,), bool).at[idx].set(True)[:nf]


def _forced_split_schedule(path: str, mappers, num_leaves: int):
    """Precompute the (leaf, feature, bin) schedule for a forced-splits JSON
    tree (reference: forcedsplits_filename, SerialTreeLearner::ForceSplits
    serial_tree_learner.cpp:620 — BFS order). Leaf ids follow the grower's
    creation-order convention (left keeps the parent's leaf id, the right
    child becomes leaf k+1)."""
    import json as _json
    from collections import deque
    with open(path) as fh:
        root = _json.load(fh)
    leaves, feats, bins = [], [], []
    queue = deque([(root, 0)])
    k = 0
    while queue and k < num_leaves - 1:
        node, leaf = queue.popleft()
        if node is None or "feature" not in node:
            continue
        f = int(node["feature"])
        thr = float(node["threshold"])
        m = mappers[f]
        if m.is_categorical:
            raise ValueError(
                "forced splits on categorical features are not supported")
        b = int(m.value_to_bin(np.array([thr]))[0])
        leaves.append(leaf)
        feats.append(f)
        bins.append(b)
        k += 1
        if node.get("left"):
            queue.append((node["left"], leaf))
        if node.get("right"):
            queue.append((node["right"], k))
    if not leaves:
        return None
    return (jnp.asarray(leaves, jnp.int32), jnp.asarray(feats, jnp.int32),
            jnp.asarray(bins, jnp.int32))


def _pick_fused_block(cfg) -> int:
    """Thin delegate: ``tpu_fused`` resolution lives in the engine
    registry (lightgbm_tpu/engines/registry.py, the ONE selection
    owner); kept under the historical name for its callers/tests."""
    from ..engines import registry as engine_registry
    return engine_registry.resolve_fused_block(cfg)


def _pick_hist_mbatch(cfg) -> int:
    """Thin delegate: ``tpu_hist_mbatch`` (user > LGBM_TPU_HIST_MBATCH
    env > autotune > default 8) resolves in the engine registry."""
    from ..engines import registry as engine_registry
    return engine_registry.resolve_mbatch(cfg)


def _pick_hist_layout(cfg, num_bins: int) -> str:
    """Thin delegate: ``tpu_hist_layout`` resolves in the engine
    registry. Without an autotune-cache decision "auto" keeps the
    conservative lane default (registry.resolve_layout makes it honest
    where a measured sublane win exists for the shape-class)."""
    from ..engines import registry as engine_registry
    return engine_registry.resolve_layout(cfg, num_bins)


def _validated_mbatch_env(value: str) -> int:
    """Thin delegate (engines/registry.py validated_mbatch_env)."""
    from ..engines import registry as engine_registry
    return engine_registry.validated_mbatch_env(value)


def _validated_fused_block_env(value: str, num_cols: int,
                               vmem_cap_bs: int) -> int:
    """Thin delegate (engines/registry.py validated_fused_block_env)."""
    from ..engines import registry as engine_registry
    return engine_registry.validated_fused_block_env(
        value, num_cols, vmem_cap_bs)


def _clamp_block(block: int, n: int, floor: int = 128) -> int:
    """Shrink a streaming block size toward the data size (power-of-two)."""
    while block // 2 >= max(n, floor) and block > floor:
        block //= 2
    return max(block, floor)


def _pick_step_buckets(cfg) -> bool:
    """Thin delegate: ``tpu_step_buckets`` (the bucketed grower-step
    ladder; ``off`` = the exact-keyed parity escape hatch) resolves in
    the engine registry."""
    from ..engines import registry as engine_registry
    return engine_registry.resolve_step_buckets(cfg)


def _pick_hist_overlap(cfg) -> int:
    """Thin delegate: ``tpu_hist_overlap`` (async histogram-collective
    overlap) resolves in the engine registry."""
    from ..engines import registry as engine_registry
    return engine_registry.resolve_overlap(cfg)


def bucketed_tree_shape(step_buckets: bool, num_leaves: int,
                        max_depth: int) -> Tuple[int, int]:
    """(num_leaves, max_depth) as they enter the GrowerParams jit key:
    the (leaf rung, depth bucket) pair under the step ladder, the exact
    values on the ``tpu_step_buckets=off`` escape hatch."""
    if step_buckets:
        return leaf_rung(num_leaves), depth_rung(max_depth)
    return num_leaves, max_depth


class HostTree:
    """Host-side copy of one grown tree (numpy struct-of-arrays)."""

    __slots__ = ("split_feature", "split_bin", "cat_bitset", "split_gain",
                 "default_left", "left_child", "right_child", "leaf_value",
                 "leaf_weight", "leaf_count", "leaf_parent", "leaf_depth",
                 "internal_value", "internal_weight", "internal_count",
                 "num_leaves", "num_nodes", "shrinkage",
                 # linear leaves (boosting/linear.py)
                 "is_linear", "leaf_const", "leaf_features", "leaf_coeff")

    def __init__(self, tree: TreeArrays, shrinkage: float = 1.0):
        self.split_feature = np.asarray(tree.split_feature)
        self.split_bin = np.asarray(tree.split_bin)
        self.cat_bitset = np.asarray(tree.cat_bitset)
        self.split_gain = np.asarray(tree.split_gain)
        self.default_left = np.asarray(tree.default_left)
        self.left_child = np.asarray(tree.left_child)
        self.right_child = np.asarray(tree.right_child)
        self.leaf_value = np.asarray(tree.leaf_value)
        self.leaf_weight = np.asarray(tree.leaf_weight)
        self.leaf_count = np.asarray(tree.leaf_count)
        self.leaf_parent = np.asarray(tree.leaf_parent)
        self.leaf_depth = np.asarray(tree.leaf_depth)
        self.internal_value = np.asarray(tree.internal_value)
        self.internal_weight = np.asarray(tree.internal_weight)
        self.internal_count = np.asarray(tree.internal_count)
        self.num_leaves = int(tree.num_leaves)
        self.num_nodes = int(tree.num_nodes)
        self.shrinkage = shrinkage
        self.is_linear = False

    def scale(self, factor: float) -> None:
        """(reference: Tree::Shrinkage, tree.h:185)"""
        self.leaf_value = self.leaf_value * factor
        self.internal_value = self.internal_value * factor
        self.shrinkage *= factor

    def add_bias(self, bias: float) -> None:
        """(reference: Tree::AddBias, called from gbdt.cpp:417)"""
        self.leaf_value = self.leaf_value + bias


def stack_trees(models: Sequence[HostTree], max_nodes: int, max_leaves: int,
                cat_w: Optional[int] = None, pad_to: Optional[int] = None
                ) -> StackedTrees:
    """Stack host trees into device arrays for batch prediction.

    ``pad_to`` pads the leading T axis (on host, before the transfer) up
    to a tree-count bucket: padding entries are all-constant trees
    (num_nodes == 0, leaf_value 0) that contribute exactly nothing, so
    the padded stack predicts identically while the jit key stays on the
    bucket. ``cat_w`` forces the categorical-bitset width (the bucketed
    cache appends new trees into existing padded arrays, so widths must
    match across fills)."""
    t = len(models)
    t_pad = max(t, pad_to or t)

    def pad2(getter, fill, dtype, width):
        out = np.full((t_pad, width), fill, dtype=dtype)
        for i, m in enumerate(models):
            a = getter(m)
            out[i, : len(a)] = a
        return jnp.asarray(out)

    cat_w = max(cat_w or 1,
                max((m.cat_bitset.shape[1] for m in models), default=1))
    cat = np.zeros((t_pad, max_nodes, cat_w), np.uint32)
    for i, m in enumerate(models):
        cb = m.cat_bitset
        cat[i, : cb.shape[0], : cb.shape[1]] = cb
    nn = np.zeros(t_pad, np.int32)
    nn[:t] = [m.num_nodes for m in models]
    return StackedTrees(
        split_feature=pad2(lambda m: m.split_feature, -1, np.int32, max_nodes),
        split_bin=pad2(lambda m: m.split_bin, 0, np.int32, max_nodes),
        cat_bitset=jnp.asarray(cat),
        default_left=pad2(lambda m: m.default_left, False, bool, max_nodes),
        left_child=pad2(lambda m: m.left_child, -1, np.int32, max_nodes),
        right_child=pad2(lambda m: m.right_child, -1, np.int32, max_nodes),
        leaf_value=pad2(lambda m: m.leaf_value, 0.0, np.float32, max_leaves),
        num_nodes=jnp.asarray(nn),
    )


def _pad_metadata(md, n_padded: int):
    """Shallow metadata clone with label/weight zero-padded to the sharded
    row count (padding rows carry zero weight and are masked out of every
    histogram/gradient by the valid-row mask)."""
    from ..io.dataset import Metadata
    out = Metadata(n_padded)
    if md.label is not None:
        out.label = np.pad(np.asarray(md.label), (0, n_padded - len(md.label)))
    # padding rows get explicit zero weight so objective label statistics
    # (boost_from_average, class balance) never count them
    n_real = len(md.label) if md.label is not None else n_padded
    w = np.ones(n_padded, np.float32) if md.weight is None \
        else np.pad(np.asarray(md.weight, np.float32), (0, n_padded - n_real))
    w[n_real:] = 0.0
    out.weight = w
    out.init_score = md.init_score
    out.group = md.group
    out.query_boundaries = md.query_boundaries
    out.position = (np.pad(np.asarray(md.position),
                           (0, n_padded - n_real))
                    if md.position is not None else None)
    return out


def _init_score_matrix(init_score, k: int, n: int) -> np.ndarray:
    """Normalize user init_score into [K, N] f32.

    Accepts [N] (k=1), 2-D [N, K] (the reference Python API's layout), or a
    flat class-major [K*N] vector (the reference Metadata's internal layout,
    src/io/metadata.cpp init_score_)."""
    arr = np.asarray(init_score, np.float32)
    if arr.ndim == 2:
        if arr.shape == (n, k):
            return arr.T
        if arr.shape == (k, n):
            return arr
        raise ValueError(f"init_score shape {arr.shape} does not match "
                         f"(num_data={n}, num_class={k})")
    if arr.size != k * n:
        raise ValueError(f"init_score size {arr.size} != num_class*num_data "
                         f"({k * n})")
    return arr.reshape(k, n)


def _device_put_like(arr, like):
    """Place a host snapshot array back on the device(s) of an existing
    array, preserving its sharding. ``make_array_from_callback`` hands each
    process only the shards it addresses, so the same global host array
    restores correctly on 1 chip, a mesh, or a multi-host pod."""
    arr = np.asarray(arr)
    if isinstance(like, jax.Array):
        return jax.make_array_from_callback(
            arr.shape, like.sharding, lambda idx: arr[idx])
    return jnp.asarray(arr)


@jax.jit
def _add_leaf_outputs(score_row, leaf_value, row_leaf):
    return score_row + leaf_value[row_leaf]


@jax.jit
def _sub_leaf_outputs(score_row, leaf_value, row_leaf):
    return score_row - leaf_value[row_leaf]


class _ValidSet:
    """Cached raw scores for one validation set
    (reference: ScoreUpdater per valid set, gbdt.cpp valid_score_updater_)."""

    def __init__(self, dataset: BinnedDataset, num_class: int, name: str,
                 mesh=None):
        self.dataset = dataset
        self.name = name
        self.n_real = dataset.num_data
        binned_np = dataset.binned
        pad = 0
        if mesh is not None:
            from ..parallel.mesh import (class_row_sharding, mesh_axis_sizes,
                                         pad_rows, row_sharding_2d)
            pad = pad_rows(self.n_real, mesh_axis_sizes(mesh)[0])
            if pad:
                binned_np = np.pad(binned_np, ((0, pad), (0, 0)))
            self.binned = jax.device_put(binned_np, row_sharding_2d(mesh))
        else:
            self.binned = jnp.asarray(binned_np)
        n = self.n_real + pad
        score0 = np.zeros((num_class, n), np.float32)
        if dataset.metadata is not None and dataset.metadata.init_score is not None:
            score0[:, : self.n_real] += _init_score_matrix(
                dataset.metadata.init_score, num_class, self.n_real)
        if mesh is not None:
            self.score = jax.device_put(score0, class_row_sharding(mesh))
        else:
            self.score = jnp.asarray(score0)
        self.metrics: List[Metric] = []


class GBDT:
    """Gradient Boosted Decision Trees (reference: class GBDT, gbdt.h)."""

    _supports_lazy_cegb = True

    boosting_type = "gbdt"
    # RF overrides: average outputs instead of summing
    average_output = False

    def __init__(
        self,
        config,
        train_set: Optional[BinnedDataset] = None,
        objective: Optional[Objective] = None,
    ):
        self.config = config
        self.objective = objective
        self.train_set = train_set
        self.models: List[HostTree] = []
        self._dev_trees: List[Tuple[TreeArrays, float]] = []
        # batched stop-check / host-materialization cadence (TPU extension;
        # 1 == reference behavior of checking every iteration)
        self.stop_check_freq = max(1, int(config.get("stop_check_freq", 1) or 1))
        self.iter_ = 0
        self.learning_rate = float(config.get("learning_rate", 0.1))
        # per-iteration shrinkage; DART re-computes this each iter
        # (reference: shrinkage_rate_, gbdt.cpp / dart.hpp DroppingTrees)
        self.shrinkage_rate = self.learning_rate
        self.num_class = int(config.get("num_class", 1))
        if objective is not None:
            self.num_tree_per_iteration = objective.num_model_per_iteration
        else:
            self.num_tree_per_iteration = self.num_class
        self.max_leaves = int(config.get("num_leaves", 31))
        self._init_scores = [0.0] * self.num_tree_per_iteration
        self.valid_sets: List[_ValidSet] = []
        self.train_metrics: List[Metric] = []
        self.best_iteration = -1
        # bucketed device-tree cache (see _device_trees_batched): per
        # tbatch, stacked trees padded to the tree-count bucket plus fill
        # metadata. APPENDED trees extend a slot in place; the cache is
        # set to None only where existing models are mutated or removed
        # (rollback, DART drops/normalization, RF vote scaling, reload)
        self._device_trees_cache: Optional[Dict[int, Dict[str, Any]]] = None
        # serializes the pending-tree flush and the device-tree cache fill,
        # so concurrent Booster.predict readers (basic.py read lock) never
        # interleave _flush_trees' models/_dev_trees mutation; re-entrant
        # because predict_raw_binned -> device_trees -> _flush_trees nests,
        # and deepcopy-safe so users can still snapshot trained models
        self._trees_mu = Mutex()
        self._comm_hlo: Dict[str, str] = {}
        self._comm_hlo_history: Dict[str, List[str]] = {}
        self._comm_hlo_sigs: Dict[str, List[tuple]] = {}
        self._comm_jitted: Dict[str, Any] = {}
        self._comm_abstract: Dict[str, tuple] = {}
        self._use_compact = False
        self._compact = None
        self.tree_learner = "serial"
        # defaults for boosters constructed without a train set (model
        # load); _setup_train overwrites them from the config
        self._step_buckets = False
        self._max_depth_cfg = int(config.get("max_depth", -1))
        # engine-registry context (engines/registry.py): the dataset
        # shape class + resolution from _setup_train, and the compact
        # record-width clamp context — reset_parameter re-resolves
        # through these so a mid-run change never leaves a stale engine
        self._engine_shape = None
        self._engine_resolution = None
        self._fused_clamp_ctx = None
        # persistent XLA compilation cache (tpu_compile_cache_dir): armed
        # before the first jit of this booster so training AND predict-only
        # programs can skip their backend compiles on a warm cache
        cache_dir = config.get("tpu_compile_cache_dir", "")
        if cache_dir:
            from ..analysis.guards import configure_compile_cache
            configure_compile_cache(cache_dir)
        # telemetry plane (lightgbm_tpu/obs): flight-ring capacity, the
        # global phase-keyed compile listener, and the per-iteration
        # metrics stream when tpu_metrics_path is set
        from .. import obs as _obs
        self._metrics_stream = _obs.configure(config)

        if train_set is not None:
            self._setup_train(train_set)

    # -- training setup ------------------------------------------------------
    def _setup_train(self, train_set: BinnedDataset) -> None:
        cfg = self.config
        from ..parallel.mesh import (class_row_sharding, make_mesh,
                                     mesh_axis_sizes, pad_rows, parse_mesh_shape,
                                     replicated, row_feature_sharding,
                                     row_sharding, row_sharding_2d)
        # multi-host bootstrap before any device queries (reference:
        # Network::Init from config, src/network/linkers_socket.cpp)
        if int(cfg.get("num_machines", 1) or 1) > 1:
            from ..parallel.multihost import init_distributed
            init_distributed(cfg)
        tree_learner = str(cfg.get("tree_learner", "serial")).lower()
        tree_learner = {"data_parallel": "data", "voting_parallel": "voting",
                        "feature_parallel": "feature"}.get(
                            tree_learner, tree_learner)
        distributed = tree_learner in ("data", "voting", "feature") \
            and len(jax.devices()) > 1
        self.tree_learner = tree_learner
        mesh_shape = parse_mesh_shape(cfg.get("tpu_mesh_shape", ""))
        self.mesh = make_mesh(mesh_shape=mesh_shape) if distributed else None
        self._multiproc = jax.process_count() > 1
        if self.mesh is not None and mesh_axis_sizes(self.mesh)[1] > 1:
            # 2-D rows x features: the masked GSPMD growers shard the bin
            # matrix over both axes; learners with a physical row layout
            # (compact's shard_map partitions, feature-parallel's
            # feature-axis placement) stay row-mesh only
            if self.tree_learner == "feature":
                raise ValueError(
                    "tpu_mesh_shape=RxC (2-D rows x features) does not "
                    "compose with tree_learner=feature — the feature "
                    "learner already owns the feature axis; use a 1-D "
                    "mesh or tree_learner=data/voting")
            if self._multiproc:
                raise ValueError(
                    "tpu_mesh_shape=RxC is single-process only for now; "
                    "multi-host runs keep the 1-D row mesh")
        if self._multiproc:
            # each process holds only its LOCAL row shard; the global array
            # is assembled below from the per-process pieces (reference:
            # pre_partition=true rank-local loading, dataset_loader.cpp:203)
            if tree_learner != "data":
                raise ValueError(
                    "multi-host training supports tree_learner=data")
            n_loc = train_set.num_data
            d_loc = len(jax.local_devices())
            if n_loc % d_loc:
                raise ValueError(
                    f"multi-host: each process's rows ({n_loc}) must divide "
                    f"its local device count ({d_loc}); pad or re-partition "
                    "the local shard")
            self._n_real = n_loc * jax.process_count()
            pad = 0
        else:
            self._n_real = train_set.num_data
            pad = pad_rows(self._n_real, mesh_axis_sizes(self.mesh)[0]) \
                if self.mesh else 0
        self._pad = pad
        self.num_data = self._n_real + pad

        # per-rank runtime attribution (obs/ranks.py): sampled step /
        # collective-wait timers + rank-0 straggler aggregation over the
        # coordination-service KV. Constructed HERE (not lazily) so the
        # collective-arrival probe compiles outside the steady-state
        # region; off-sample iterations touch none of it.
        self._rank_stats = None
        rs_every = int(cfg.get("tpu_rank_stats_every", 0) or 0)
        if rs_every > 0:
            from ..obs.ranks import RankStats
            self._rank_stats = RankStats(
                every=rs_every,
                straggler_factor=float(
                    cfg.get("tpu_straggler_factor", 3.0) or 3.0),
                mesh=self.mesh,
                deadline_s=float(
                    cfg.get("tpu_collective_deadline_s", 0.0) or 0.0),
                stream=self._metrics_stream)

        # EFB: configurations the bundle-space growers can't serve unbundle
        # HERE, before any device placement, so every learner's layout logic
        # below sees a plain dense matrix (bundling is lossless)
        self._efb_precheck(train_set, cfg, tree_learner)

        binned_np = train_set.binned
        if pad:
            binned_np = np.pad(binned_np, ((0, pad), (0, 0)))
        # feature-parallel shards the feature axis; pad it to the mesh size
        # with trivial (never-selectable) features
        self._f_pad = 0
        if self.mesh is not None and self.tree_learner == "feature":
            self._f_pad = (-binned_np.shape[1]) % len(
                self.mesh.devices.ravel())
            if self._f_pad:
                binned_np = np.pad(binned_np, ((0, 0), (0, self._f_pad)))
            # feature-parallel: data replicated, split finding partitioned by
            # feature (reference: feature_parallel_tree_learner.cpp — every
            # rank holds full data; GSPMD shards the [F, B] histogram/scan
            # over features and all-gathers the tiny best-split argmax, the
            # analogue of SyncUpGlobalBestSplit)
            from ..parallel.mesh import feature_sharding_2d
            self.binned = jax.device_put(binned_np,
                                         feature_sharding_2d(self.mesh))
            ones = np.ones(self.num_data, np.float32)
            if pad:
                ones[self._n_real:] = 0.0
            self._valid_row_mask = jax.device_put(
                ones, replicated(self.mesh)) if pad else None
        elif self.mesh is not None:
            # rows sharded over the mesh: the reference's row partitioning
            # across machines (data_parallel_tree_learner.cpp BeforeTrain)
            if self._multiproc:
                # assemble the global array from per-process local shards
                self.binned = jax.make_array_from_process_local_data(
                    row_sharding_2d(self.mesh), binned_np)
                self._valid_row_mask = None
            else:
                s_feat = mesh_axis_sizes(self.mesh)[1]
                if s_feat > 1:
                    # 2-D mesh: the feature axis shards too — pad it with
                    # trivial (never-selectable) columns like the
                    # feature-parallel learner does
                    self._f_pad = (-binned_np.shape[1]) % s_feat
                    if self._f_pad:
                        binned_np = np.pad(binned_np,
                                           ((0, 0), (0, self._f_pad)))
                self.binned = jax.device_put(
                    binned_np, row_feature_sharding(self.mesh))
                ones = np.ones(self.num_data, np.float32)
                if pad:
                    ones[self._n_real:] = 0.0
                self._valid_row_mask = jax.device_put(
                    ones, row_sharding(self.mesh))
        else:
            self.binned = jnp.asarray(binned_np)
            self._valid_row_mask = None
        def fpad(arr, fill):
            if self._f_pad:
                return np.concatenate(
                    [np.asarray(arr),
                     np.full(self._f_pad, fill, np.asarray(arr).dtype)])
            return np.asarray(arr)

        self.num_bins_arr = jnp.asarray(
            fpad(train_set.feature_num_bins(), 1))
        self.nan_bin_arr = jnp.asarray(fpad(train_set.feature_nan_bins(), 0))
        self.has_nan_arr = jnp.asarray(fpad(
            np.array([m.missing_type == 2 and not m.is_categorical
                      for m in train_set.mappers], dtype=bool), False))
        self.is_cat_arr = jnp.asarray(fpad(
            train_set.feature_is_categorical(), False))
        self.base_feat_mask = fpad(np.array(
            [not m.is_trivial for m in train_set.mappers], dtype=bool), False)
        # inference-engine flags: prediction inputs arrive in ORIGINAL
        # feature space, so categorical presence and 4-bit-pack
        # eligibility come from the raw mappers (ops/predict.py engine)
        self._pred_any_cat = bool(np.any(train_set.feature_is_categorical()))
        from ..io.dataset import pack4_eligible
        want_pack4 = bool(cfg.get("tpu_bin_pack4", False))
        self._pred_pack4 = want_pack4 and pack4_eligible(train_set.mappers)
        if want_pack4 and not self._pred_pack4:
            log.warning("tpu_bin_pack4=true needs every feature to have "
                        "<= 16 bins (max_bin <= 15); serving the u8 matrix")

        nf = train_set.num_total_features
        mono_np = _parse_monotone(cfg.get("monotone_constraints"), nf,
                                  train_set.feature_names)
        inter_np = _parse_interactions(
            cfg.get("interaction_constraints"), nf)
        self._mono_types = (jnp.asarray(fpad(mono_np, 0))
                            if mono_np is not None else None)
        mono_method = str(cfg.get("monotone_constraints_method", "basic"))
        self._mono_intermediate = (mono_np is not None
                                   and mono_method in ("intermediate",
                                                       "advanced"))
        if mono_np is not None and mono_method == "advanced":
            log.warning(
                "monotone_constraints_method='advanced' is not implemented; "
                "using the 'intermediate' method")
        if inter_np is not None and self._f_pad:
            inter_np = np.pad(inter_np, ((0, 0), (0, self._f_pad)))
        self._inter_sets = (jnp.asarray(inter_np) if inter_np is not None
                            else None)
        self._bynode_key = jax.random.PRNGKey(
            int(cfg.get("feature_fraction_seed", 2)))
        # CEGB (reference: cost_effective_gradient_boosting.hpp): coupled
        # feature costs are paid once per model, so the used-feature set
        # persists across trees
        tradeoff = float(cfg.get("cegb_tradeoff", 1.0))

        def _vec(v):
            # config files / CLI deliver vector params as comma strings
            if isinstance(v, str):
                return [float(t) for t in v.split(",") if t.strip()]
            return list(v)

        coupled = cfg.get("cegb_penalty_feature_coupled")
        split_pen = float(cfg.get("cegb_penalty_split", 0.0))
        self._use_cegb = split_pen > 0.0 or coupled is not None
        lazy = cfg.get("cegb_penalty_feature_lazy")
        if lazy is not None and not self._supports_lazy_cegb:
            # RF (and any other subclass that opts out) must decline BEFORE
            # the bitmap size check / EFB precheck act on the parameter
            log.warning("cegb_penalty_feature_lazy is not supported with "
                        f"boosting={self.boosting_type}; the lazy penalty "
                        "is ignored")
            lazy = None
        if lazy is not None:
            lz = np.asarray(_vec(lazy), np.float32)
            if lz.size != nf:
                raise ValueError(
                    "cegb_penalty_feature_lazy must have one entry per "
                    f"feature ({nf}), got {lz.size}")
            # on-demand (lazy) per-row feature costs: the [F, N] bool
            # bitmap plus its transient f32 cast in the per-split matvec
            # cost ~5 bytes per element on device — bound well inside HBM
            nf_pad = nf + self._f_pad
            if nf_pad * self.num_data > (1 << 30):
                raise ValueError(
                    "cegb_penalty_feature_lazy needs an [F, N] charged-rows "
                    f"bitmap (~5 bytes/element transient); "
                    f"{nf_pad}x{self.num_data} exceeds the supported size "
                    "(2^30 elements)")
            self._cegb_lazy = jnp.asarray(
                fpad(tradeoff * lz, 0.0)) if self._f_pad else \
                jnp.asarray(tradeoff * lz)
            self._use_cegb = True
        else:
            self._cegb_lazy = None
        self._cegb_charged = None  # lazily a [F, N] bool device array
        if coupled is not None:
            cp = np.asarray(_vec(coupled), np.float32)
            if cp.size != nf:
                raise ValueError(
                    "cegb_penalty_feature_coupled must have one entry per "
                    f"feature ({nf}), got {cp.size}")
            self._cegb_coupled = jnp.asarray(
                fpad(tradeoff * cp, 0.0)) if self._f_pad else \
                jnp.asarray(tradeoff * cp)
        else:
            self._cegb_coupled = None
        self._cegb_split_pen = tradeoff * split_pen
        self._cegb_used = None  # lazily a [F] bool device array
        # quantized-gradient training (reference: gradient_discretizer.cpp)
        self._linear = bool(cfg.get("linear_tree", False)) \
            and self.mesh is None and self.boosting_type == "gbdt"
        if bool(cfg.get("linear_tree", False)) \
                and self.boosting_type != "gbdt":
            log.warning(f"linear_tree is not supported with "
                        f"boosting={self.boosting_type}; training constant "
                        "leaves")
        if self._linear and train_set.raw_data is None:
            raise ValueError(
                "linear_tree=true needs raw feature values; construct the "
                "Dataset with the linear_tree parameter set (or "
                "free_raw_data=False) so they are retained")
        if bool(cfg.get("linear_tree", False)) and self.mesh is not None:
            log.warning("linear_tree is not supported with distributed "
                        "tree learners; training constant leaves")
        self._use_quant = bool(cfg.get("use_quantized_grad", False))
        # set for real in _build_compact_step_fn (the int pipeline is
        # compact-only); defaulting here keeps introspection safe on the
        # masked path
        self._quant_narrow_active = False
        self._quant_bins = int(cfg.get("num_grad_quant_bins", 4))
        self._quant_renew = bool(cfg.get("quant_train_renew_leaf", False))
        self._quant_stochastic = bool(cfg.get("stochastic_rounding", True))
        self._quant_key = jax.random.PRNGKey(
            int(cfg.get("seed", 0) or 0) + 1337)
        self._extra_key = jax.random.PRNGKey(int(cfg.get("extra_seed", 6)))
        fs_path = str(cfg.get("forcedsplits_filename", "") or "")
        if fs_path and self.mesh is not None and self.tree_learner == "voting":
            # voted histograms zero un-elected features, so forced child
            # sums would be wrong (grower reads them from leaf_hist)
            log.warning("forcedsplits_filename is not supported with "
                        "tree_learner=voting; ignoring it")
            fs_path = ""
        self._forced_splits = _forced_split_schedule(
            fs_path, train_set.mappers, self.max_leaves) if fs_path else None
        fc = cfg.get("feature_contri")
        if fc is not None:
            fcv = np.asarray(list(fc), np.float32)
            if fcv.size != nf:
                raise ValueError("feature_contri needs one entry per feature")
            self._feature_contri = jnp.asarray(
                fpad(fcv, 1.0)) if self._f_pad else jnp.asarray(fcv)
        else:
            self._feature_contri = None
        # THE engine-registry callsite (lightgbm_tpu/engines/registry.py):
        # one resolve populates every engine knob of GrowerParams —
        # {fused, pallas, xla} x layout x batched-M x ladder x overlap —
        # user > env > autotune cache > heuristic default. With
        # tpu_autotune armed the startup microbench times the eligible
        # candidates on a strided sample of the REAL binned matrix
        # (strictly before the steady-state window; compiles land in the
        # "autotune" phase) and persists the per-shape-class winner.
        from ..engines import registry as engine_registry
        binned_host = train_set.binned
        shape = engine_registry.DatasetShape(
            rows=int(self._n_real),
            # STORED columns (post-EFB): the width the histogram engines
            # actually stream, and the width the microbench sample has
            features=int(binned_host.shape[1]),
            num_bins=int(train_set.max_num_bins),
            mode=(self.tree_learner if self.mesh is not None
                  or self._multiproc else "serial"),
            quant=bool(cfg.get("use_quantized_grad", False)),
            pack4=bool(cfg.get("tpu_bin_pack4", False)))

        def _autotune_sample(n, _b=binned_host):
            if len(_b) <= n:
                return _b
            stride = max(1, len(_b) // n)
            return _b[::stride][:n]

        self._engine_shape = shape
        resolved = engine_registry.resolve(
            cfg, shape=shape, sample_provider=_autotune_sample)
        self._engine_resolution = resolved

        # bucketed step ladder (the compile-once training contract): the
        # jit key carries (leaf rung, depth bucket), the actual budgets
        # ride as traced scalars through _step_budget_args()
        self._step_buckets = resolved.step_buckets
        self._max_depth_cfg = int(cfg.get("max_depth", -1))
        key_leaves, key_depth = bucketed_tree_shape(
            self._step_buckets, self.max_leaves, self._max_depth_cfg)
        self.grower_params = GrowerParams(
            num_leaves=key_leaves,
            max_depth=key_depth,
            step_buckets=self._step_buckets,
            hist_overlap=resolved.hist_overlap,
            num_bins=int(train_set.max_num_bins),
            lambda_l1=float(cfg.get("lambda_l1", 0.0)),
            lambda_l2=float(cfg.get("lambda_l2", 0.0)),
            min_data_in_leaf=float(cfg.get("min_data_in_leaf", 20)),
            min_sum_hessian_in_leaf=float(cfg.get("min_sum_hessian_in_leaf", 1e-3)),
            min_gain_to_split=float(cfg.get("min_gain_to_split", 0.0)),
            max_delta_step=float(cfg.get("max_delta_step", 0.0)),
            max_cat_threshold=int(cfg.get("max_cat_threshold", 32)),
            cat_l2=float(cfg.get("cat_l2", 10.0)),
            cat_smooth=float(cfg.get("cat_smooth", 10.0)),
            max_cat_to_onehot=int(cfg.get("max_cat_to_onehot", 4)),
            min_data_per_group=float(cfg.get("min_data_per_group", 100)),
            any_cat=bool(np.any(train_set.feature_is_categorical())),
            use_monotone=mono_np is not None,
            monotone_penalty=float(cfg.get("monotone_penalty", 0.0)),
            mono_intermediate=self._mono_intermediate,
            path_smooth=float(cfg.get("path_smooth", 0.0)),
            use_interaction=inter_np is not None,
            bynode_fraction=float(cfg.get("feature_fraction_bynode", 1.0)),
            use_cegb=self._use_cegb,
            cegb_split_pen=self._cegb_split_pen,
            extra_trees=bool(cfg.get("extra_trees", False)),
            voting_k=(int(cfg.get("top_k", 20))
                      if self.mesh is not None
                      and self.tree_learner == "voting" else 0),
            voting_shards=(mesh_axis_sizes(self.mesh)[0]
                           if self.mesh is not None
                           and self.tree_learner == "voting" else 0),
            hist_impl=resolved.hist_impl,
            part_block=_clamp_block(
                int(cfg.get("tpu_part_block", 2048)), self._n_real),
            hist_block=_clamp_block(
                int(cfg.get("tpu_hist_block", 16384)), self._n_real),
            fused_block=resolved.fused_block,
            fused_interpret=bool(cfg.get("tpu_fused_interpret", False)),
            hist_mbatch=resolved.hist_mbatch,
            hist_layout=resolved.hist_layout,
        )

        # serial-learner row storage: the compact grower physically
        # partitions rows into per-leaf segments — O(N*depth) per tree
        # instead of the masked grower's O(N*num_leaves) (see
        # ops/grower_compact.py). It requires row-elementwise gradients
        # (the rows live in a per-tree permuted order).
        grower = str(cfg.get("tpu_grower", "auto")).lower()
        # data-parallel: the compact grower runs per shard under shard_map,
        # with shard-local partitions and psum-ed histograms (reference:
        # DataParallelTreeLearner, data_parallel_tree_learner.cpp:223-300);
        # voting/feature learners keep the masked GSPMD path
        mesh_compact_ok = (
            self.mesh is None
            or (self.tree_learner == "data"
                and mesh_axis_sizes(self.mesh)[1] == 1
                and not (self.objective is not None
                         and self.objective.renew_leaves)))
        # exact-count ceiling: histogram count channels ride f32, exact for
        # integers < 2^24; the partition-critical counts are SHARD-LOCAL
        # under the data-parallel learner (n_left_loc from the shard's own
        # histogram), so the bound applies per shard, not globally. Global
        # psum-ed counts only feed constraints (min_data) and the
        # smaller-side election, where +-2^-24 relative is harmless.
        n_shards = (mesh_axis_sizes(self.mesh)[0]
                    if self.mesh is not None and self.tree_learner == "data"
                    else 1)
        # non-row-elementwise objectives (lambdarank: gradients couple rows
        # of a query) still run compact when K == 1: gradients compute
        # on-device in ORIGINAL row order (scatter by the carried row-id
        # column) and feed the step externally — see _rank_grads_fn
        obj_re = (getattr(self.objective, "row_elementwise", True)
                  if self.objective is not None else False)
        goss = (str(cfg.get("data_sample_strategy", "bagging")).lower()
                == "goss"
                or str(cfg.get("boosting", "gbdt")).lower() == "goss")
        self._ext_grads = (
            not obj_re and int(cfg.get("num_class", 1) or 1) == 1
            and not goss and not bool(cfg.get("use_quantized_grad", False)))
        can_compact = (
            mesh_compact_ok
            and self.objective is not None
            and (obj_re or self._ext_grads)
            and not getattr(self.objective, "is_stochastic", False)
            and int(train_set.max_num_bins) <= 256
            and -(-self.num_data // n_shards) < (1 << 24)
            # balanced / by-query bagging index rows in the original order
            and float(cfg.get("pos_bagging_fraction", 1.0)) >= 1.0
            and float(cfg.get("neg_bagging_fraction", 1.0)) >= 1.0
            and not bool(cfg.get("bagging_by_query", False))
            # lazy CEGB tracks charged rows in ORIGINAL row order; the
            # compact grower permutes rows, so it runs masked
            and (cfg.get("cegb_penalty_feature_lazy") is None
                 or not self._supports_lazy_cegb)
        )
        if grower == "compact" and not can_compact:
            log.warning("tpu_grower=compact requires a serial learner and a "
                        "row-elementwise objective; using masked grower")
        # linear leaves fit against raw rows in the ORIGINAL order; the
        # compact grower permutes rows, so linear mode uses the masked path;
        # forced splits are implemented in the masked grower only
        can_compact = can_compact and not self._linear \
            and self._forced_splits is None
        self._use_compact = can_compact and (
            grower == "compact"
            # bundled datasets always prefer the compact grower: the
            # bundle-space scan/routing lives there, and the masked grower
            # would otherwise unbundle back to the dense width
            or (grower == "auto"
                and (self._n_real >= 65536
                     or getattr(train_set, "bundle_info", None) is not None)))
        self._compact = None          # lazy _CompactTrainState
        if self._mono_intermediate and not self._use_compact:
            log.warning(
                "monotone_constraints_method='intermediate' runs on the "
                "compact grower only; this configuration uses the masked "
                "grower with the 'basic' method")
            self.grower_params = self.grower_params._replace(
                mono_intermediate=False)
        self._setup_efb(train_set)
        md = train_set.metadata if not pad else _pad_metadata(
            train_set.metadata, self.num_data)
        if self._multiproc:
            # label/weight/... become the host-side GLOBAL arrays on every
            # process (metrics, averages and objectives are global state)
            from ..parallel.multihost import gather_metadata
            md = gather_metadata(train_set.metadata, train_set.num_data)
        self._global_md = md if self._multiproc else None
        if self.objective is not None:
            self.objective.init(md, self.num_data)

        k, n = self.num_tree_per_iteration, self.num_data
        score0 = np.zeros((k, n), np.float32)
        if md.init_score is not None:
            init = _init_score_matrix(md.init_score, k, self._n_real)
            score0[:, : self._n_real] += init
            self._has_init_score = True
        else:
            self._has_init_score = False
        if self.mesh is not None and self.tree_learner != "feature":
            self.train_score = jax.device_put(
                score0, class_row_sharding(self.mesh))
        elif self.mesh is not None:
            self.train_score = jax.device_put(score0, replicated(self.mesh))
        else:
            self.train_score = jnp.asarray(score0)

        self.sample_strategy = create_sample_strategy(cfg, self.num_data, md)
        self.feature_fraction = float(cfg.get("feature_fraction", 1.0))
        self._feat_rng = np.random.RandomState(
            int(cfg.get("feature_fraction_seed", 2)))
        self.row_weight = (
            jnp.asarray(md.weight, jnp.float32)
            if md.weight is not None else None)
        self._grad_fn = None
        self._step_fn = None
        self._comm_hlo = {}
        self._comm_hlo_history = {}
        self._comm_hlo_sigs = {}
        self._comm_jitted = {}
        self._comm_abstract = {}

    def _step_budget_args(self) -> Tuple[jax.Array, jax.Array]:
        """(leaf_budget, depth_budget) — the ACTUAL tree budgets as traced
        i32 scalars for the bucketed step ladder. Device scalars are cached
        per value so steady-state iterations re-feed the same arrays
        (passed on the exact-keyed path too, where the growers ignore them
        — dead args keep one call signature per mode)."""
        vals = (int(self.max_leaves), int(self._max_depth_cfg))
        cached = getattr(self, "_budget_cache", None)
        if cached is None or cached[0] != vals:
            self._budget_cache = (vals, (jnp.asarray(vals[0], jnp.int32),
                                         jnp.asarray(vals[1], jnp.int32)))
        return self._budget_cache[1]

    def _build_step_fn(self):
        """One fused, jitted train step per tree: mask gradients, grow, renew,
        shrink, update the train score — a single XLA program, zero host syncs
        (the contract of the reference's CUDA path, SURVEY §3.3)."""
        obj = self.objective
        renew = obj is not None and obj.renew_leaves
        row_weight = self.row_weight
        grower_params = self.grower_params
        num_bins_arr = self.num_bins_arr
        nan_bin_arr = self.nan_bin_arr
        has_nan_arr = self.has_nan_arr
        is_cat_arr = self.is_cat_arr
        # leaf-array length of the grown trees: the RUNG under the step
        # ladder (renew scatters and liveness masks must match the
        # grower's padded leaf arrays, not the user's leaf count)
        max_leaves = self.grower_params.num_leaves

        mono_types = self._mono_types
        inter_sets = self._inter_sets
        cegb_coupled = self._cegb_coupled
        use_cegb = self._use_cegb
        use_quant = self._use_quant
        quant_renew = use_quant and self._quant_renew
        quant_bins = self._quant_bins
        quant_stoch = self._quant_stochastic
        const_hess = bool(getattr(obj, "is_constant_hessian", False))
        feature_contri = self._feature_contri

        def step(binned, score_k, grad_k, hess_k, mask, feat_mask,
                 shrinkage, bynode_key, cegb_used, true_grad_k, true_hess_k,
                 extra_key, cegb_charged, leaf_budget, depth_budget):
            # binned is an argument, not a closure: multi-process global
            # arrays cannot be captured as jit constants
            # grad_k/hess_k arrive already quantized when use_quantized_grad
            # (once per iteration over all classes, like the reference's
            # GradientDiscretizer); true_* carry the originals for renewal
            g = grad_k * mask
            h = hess_k * mask
            if use_lazy:
                tree, row_leaf, cegb_charged = grow_tree(
                    binned, g, h, mask, num_bins_arr, nan_bin_arr,
                    has_nan_arr, is_cat_arr, feat_mask, grower_params,
                    mono_types, inter_sets, bynode_key, cegb_coupled,
                    cegb_used, extra_key, feature_contri,
                    self._forced_splits, cegb_lazy=self._cegb_lazy,
                    cegb_charged0=cegb_charged, leaf_budget=leaf_budget,
                    depth_budget=depth_budget)
            else:
                tree, row_leaf = grow_tree(
                    binned, g, h, mask, num_bins_arr, nan_bin_arr,
                    has_nan_arr, is_cat_arr, feat_mask, grower_params,
                    mono_types, inter_sets, bynode_key, cegb_coupled,
                    cegb_used, extra_key, feature_contri,
                    self._forced_splits, leaf_budget=leaf_budget,
                    depth_budget=depth_budget)
            if use_cegb:
                cegb_used = _tree_used_features(tree, binned.shape[1],
                                                cegb_used)
            if quant_renew:
                # re-fit leaf outputs from the TRUE gradient sums
                # (reference: RenewIntGradTreeOutput, gbdt.cpp)
                tg = true_grad_k * mask
                th = true_hess_k * mask
                sums_g = jnp.zeros((max_leaves,)).at[row_leaf].add(tg)
                sums_h = jnp.zeros((max_leaves,)).at[row_leaf].add(th)
                from ..ops.split import leaf_output as _lo
                live = jnp.arange(max_leaves) < tree.num_leaves
                tree = tree._replace(leaf_value=jnp.where(
                    live, _lo(sums_g, sums_h, grower_params.split_params()),
                    tree.leaf_value))
            if renew:
                residual = obj.label - score_k
                w = mask if row_weight is None else mask * row_weight
                renewed = renew_leaf_quantile(
                    residual, w, row_leaf, max_leaves, float(obj.renew_alpha))
                live = jnp.arange(max_leaves) < tree.num_leaves
                tree = tree._replace(
                    leaf_value=jnp.where(live, renewed, tree.leaf_value))
            # a no-split tree contributes nothing (reference: AsConstantTree 0,
            # gbdt.cpp:433) — zeroing here lets the host defer its stop check
            # without score corruption (no per-iteration device->host sync)
            lv = jnp.where(tree.num_nodes > 0, tree.leaf_value, 0.0)
            tree = tree._replace(
                leaf_value=lv * shrinkage,
                internal_value=tree.internal_value * shrinkage)
            new_score = score_k + tree.leaf_value[row_leaf]
            return tree, row_leaf, new_score, cegb_used, cegb_charged

        use_lazy = self._cegb_lazy is not None
        jitted = jax.jit(step)
        if os.environ.get("LGBM_TPU_COMM_ACCOUNTING", "") == "1":
            return self._comm_capture(jitted, "step")
        return jitted

    # comm-volume accounting (dryrun_multichip) and the hlo_check contract
    # gate: compiled-HLO text of the train-step programs, captured when
    # LGBM_TPU_COMM_ACCOUNTING=1 so the collectives XLA actually inserted
    # can be parsed back out (analysis/hlo.py)
    _comm_hlo: Dict[str, str]

    def _comm_capture(self, jitted, key):
        """Wrap a jitted step for LGBM_TPU_COMM_ACCOUNTING=1 runs.

        Records the compiled HLO text under ``key`` on the first call and
        re-lowers whenever the abstract argument signature changes, so
        ``analysis/hlo_check.py`` can both verify the steady-state program
        against its contract and prove it stable across iterations — a
        recompile detector at the HLO level, not just the event counter
        (``_comm_hlo_history[key]`` holds one text per distinct signature;
        length 1 == the step never re-lowered)."""
        key_of = key if callable(key) else (lambda kwargs: key)

        def capture(*args, **kwargs):
            k = key_of(kwargs)
            sig = tuple(
                (tuple(x.shape), str(x.dtype))
                for x in jax.tree_util.tree_leaves((args, kwargs))
                if hasattr(x, "shape"))
            seen = self._comm_hlo_sigs.setdefault(k, [])
            if sig not in seen:
                seen.append(sig)
                # AOT re-lowering hook (analysis/spmd_check.py): the jitted
                # callable + the abstract (shape/dtype/sharding) argument
                # signature — enough to re-lower this program at a DIFFERENT
                # row count without data (ShapeDtypeStructs hold no buffers,
                # so donated args are not retained)
                self._comm_jitted[k] = jitted
                self._comm_abstract[k] = (
                    [self._abstractify(a) for a in args],
                    {kk: self._abstractify(v) for kk, v in kwargs.items()})
                text = jitted.lower(*args, **kwargs).compile().as_text()
                self._comm_hlo.setdefault(k, text)
                self._comm_hlo_history.setdefault(k, []).append(text)
                # flight-recorder accounting: the collectives XLA actually
                # inserted into this program, in bytes per step — a dead
                # run's dump carries its own comm inventory
                try:
                    from ..analysis.hlo import collective_bytes
                    from ..obs import flight
                    bts = collective_bytes(text)
                    flight.note("collective_program", key=k,
                                bytes={kk: v for kk, v in bts.items()
                                       if kk not in ("total", "count")
                                       and v},
                                total=bts.get("total", 0),
                                count=bts.get("count", 0),
                                relowered=len(self._comm_hlo_history[k]) - 1)
                except Exception:  # noqa: BLE001 - accounting best-effort
                    pass
            return jitted(*args, **kwargs)
        return capture

    @staticmethod
    def _abstractify(x):
        """jax.Array leaves -> sharded ShapeDtypeStructs (AOT signature).

        Only NAMED (mesh) shardings are pinned: a single-device placement
        on an auxiliary arg (e.g. an uncommitted bag vector) must stay
        unconstrained, or relowering under the mesh reports an
        incompatible-devices conflict the real call never had."""
        from jax.sharding import NamedSharding

        def leaf(v):
            if isinstance(v, jax.Array):
                sh = v.sharding if isinstance(v.sharding, NamedSharding) \
                    else None
                return jax.ShapeDtypeStruct(v.shape, v.dtype, sharding=sh)
            return v
        return jax.tree_util.tree_map(leaf, x)

    def aot_lower_program(self, key: str, dim_map: Optional[Dict[int, int]]
                          = None):
        """AOT-relower a comm-captured step program at rewritten row dims.

        The spmd flight check's scaling hook: a tiny training run under
        ``LGBM_TPU_COMM_ACCOUNTING=1`` records the jitted step and its
        abstract argument signature; this re-lowers the SAME program with
        every dimension in ``dim_map`` rewritten (e.g. the padded tiny
        row count -> the full Allstate row count) — shapes only, no data
        is materialized, so a 13.2M-row program lowers on this CPU host
        in compile time, not memory. Shardings ride the recorded
        ShapeDtypeStructs, so the mesh placement is the captured run's.
        Returns the ``jax.stages.Lowered`` (call ``.compile()`` for the
        partitioned per-chip HLO text).
        """
        if key not in self._comm_jitted:
            raise KeyError(
                f"program {key!r} was not comm-captured (have "
                f"{sorted(self._comm_jitted)}); train at least one "
                "iteration with LGBM_TPU_COMM_ACCOUNTING=1 first")
        args, kwargs = self._comm_abstract[key]

        def resize(x):
            if isinstance(x, jax.ShapeDtypeStruct) and dim_map:
                shape = tuple(dim_map.get(d, d) for d in x.shape)
                if shape != tuple(x.shape):
                    return jax.ShapeDtypeStruct(shape, x.dtype,
                                                sharding=x.sharding)
            return x

        args = [jax.tree_util.tree_map(resize, a) for a in args]
        kwargs = {k: jax.tree_util.tree_map(resize, v)
                  for k, v in kwargs.items()}
        return self._comm_jitted[key].lower(*args, **kwargs)

    def flight_row_dims(self, n_rows: int) -> Dict[int, int]:
        """``dim_map`` for :meth:`aot_lower_program`: every captured
        row-proportional dimension -> its value at ``n_rows`` real rows.

        Two row dims exist: the mesh-padded global row count
        (``num_data``) and, for the compact grower, the work/scratch row
        count ``S * (n/S + pad_rows)`` (each shard's rows plus its own
        block-overrun pad — see ``_setup_compact_state``)."""
        from ..parallel.mesh import mesh_axis_sizes, pad_rows
        s_rows = (mesh_axis_sizes(self.mesh)[0]
                  if self.mesh is not None else 1)
        n_pad = n_rows + pad_rows(n_rows, s_rows)
        dim_map = {int(self.num_data): int(n_pad)}
        c = getattr(self, "_compact", None)
        if c and c.get("work") is not None:
            new_rows = c["S"] * (n_pad // c["S"] + c["pad_rows"])
            dim_map[int(c["work"].shape[0])] = int(new_rows)
        return dim_map

    def aot_lower_sharded_predict(self, n_rows: int):
        """AOT-lower the GSPMD row-sharded serving dispatch (the
        ``predict_raw_device`` oversize branch) at ``n_rows`` rows over
        the training mesh — the spmd flight check's serving program.
        Abstract input only: nothing is featurized or transferred."""
        if self.mesh is None:
            raise ValueError(
                "sharded predict needs a training mesh (tree_learner="
                "data/voting/feature on >1 device)")
        from ..parallel.mesh import (mesh_axis_sizes, predict_shard_pad,
                                     replicated, row_sharding_2d)
        tb_cfg, ladder, _engine = self._predict_cfg()
        nan_a, cat_a = self._pred_route_args()
        st, t_real, depth = self._device_trees_batched(None, 0, tb_cfg)
        if t_real == 0:
            raise ValueError("no trees to lower (train first)")
        num_shards = mesh_axis_sizes(self.mesh)[0]
        n_pad = predict_shard_pad(n_rows, num_shards, ladder)
        if n_pad is None:
            # per-shard share above the ladder: lower at the top rung —
            # the program the slicing fallback would run per slice
            n_pad = ladder[-1] * num_shards
        packed = self._pred_pack4
        f = self.train_set.num_total_features
        cols = (f + 1) // 2 if packed else f
        rep = replicated(self.mesh)
        shaped = self._abstractify
        rep_abs = jax.tree_util.tree_map(
            lambda v: jax.ShapeDtypeStruct(v.shape, v.dtype, sharding=rep)
            if isinstance(v, jax.ShapeDtypeStruct) else v, shaped(
                (st, nan_a, cat_a)))
        st_a, nan_abs, cat_abs = rep_abs
        k = self.num_tree_per_iteration
        ab = jax.ShapeDtypeStruct(
            (n_pad, cols), self.train_set.binned.dtype,
            sharding=row_sharding_2d(self.mesh))
        kk = jax.ShapeDtypeStruct((), jnp.int32, sharding=rep)
        return predict_raw_batched.lower(
            ab, st_a, nan_abs, cat_abs, kk, num_class=k,
            depth=depth_bucket(depth), tbatch=tb_cfg,
            any_cat=self._pred_any_cat, packed=packed)

    def aot_lower_serving(self, engine: str, n_rows: Optional[int] = None):
        """AOT-lower one serving engine's predict program ("walk" or
        "level") at a ladder rung with abstract inputs — the
        serving-contract harness (analysis/hlo_check
        verify_serving_contracts). Nothing is featurized or
        transferred; returns the ``jax.stages.Lowered``."""
        tb_cfg, ladder, _ = self._predict_cfg()
        nan_a, cat_a = self._pred_route_args()
        st, t_real, depth, c = self._device_trees_entry(None, 0, tb_cfg)
        if t_real == 0:
            raise ValueError("no trees to lower (train first)")
        rung = int(ladder[0]) if n_rows is None \
            else bucket_rows(n_rows, ladder)
        packed = self._pred_pack4
        f = self.train_set.num_total_features
        cols = (f + 1) // 2 if packed else f
        ab = jax.ShapeDtypeStruct((rung, cols), self.train_set.binned.dtype)
        kk = jax.ShapeDtypeStruct((), jnp.int32)
        k = self.num_tree_per_iteration
        if engine == "level":
            lvt_a, lv_a = self._abstractify(
                (self._level_state(c, depth), st.leaf_value))
            return predict_raw_level.lower(
                ab, lvt_a, lv_a, kk, num_class=k, depth=max(1, depth),
                tbatch=tb_cfg, any_cat=self._pred_any_cat, packed=packed)
        if engine != "walk":
            raise ValueError(f"unknown serving engine {engine!r} "
                             "(walk|level)")
        st_a, nan_abs, cat_abs = self._abstractify((st, nan_a, cat_a))
        return predict_raw_batched.lower(
            ab, st_a, nan_abs, cat_abs, kk, num_class=k,
            depth=depth_bucket(depth), tbatch=tb_cfg,
            any_cat=self._pred_any_cat, packed=packed)

    # -- compact (physically partitioned) serial path ------------------------
    def _setup_compact_state(self) -> None:
        """Build the packed row-record arrays for the compact grower
        (ops/grower_compact.py). Extras carried through every partition:
        [scores(K), objective label, objective weight?, original row id]."""
        obj = self.objective
        n = self.num_data
        if n >= (1 << 24):
            # f32 raw-count histograms drive the partition offsets and f32
            # row ids drive the metric permutation; both are exact only
            # below 2^24 rows (ops/compact.py)
            raise RuntimeError(
                "tpu_grower=compact supports up to 2^24 rows; use "
                "tree_learner=data to shard rows or tpu_grower=masked")
        k = self.num_tree_per_iteration
        has_w = obj.weight is not None
        # extras: [scores(K), grads(K-1 extra pairs for multiclass), label,
        # weight?, rowid]. For K>1 the per-class gradients are computed once
        # per iteration (reference: GBDT::Boosting before the class-tree
        # loop, gbdt.cpp:220) and must ride the permutations of earlier
        # same-iteration trees, so they live in carried columns.
        self._cx_grads = k if k > 1 else None
        gcols = 2 * k if k > 1 else 0
        e = k + gcols + 1 + (1 if has_w else 0) + 1
        # pack4 TRAINING (reference: the 4-bit dense bin store,
        # src/io/dense_bin.hpp DenseBin<true>): when every STORED column
        # realizes <= 16 bins AND the shape-stable histogram width fits a
        # nibble, the work/scratch bin columns nibble-pack — the streamed
        # bin bytes (the fused kernel's dominant HBM traffic) halve, and
        # every consumer unpacks per block/nibble at its read site
        pack4_train = False
        if bool(self.config.get("tpu_bin_pack4", False)):
            from ..io.dataset import pack4_train_eligible
            nb_max = int(np.asarray(self.num_bins_arr).max())
            if pack4_train_eligible(np.asarray(self.num_bins_arr),
                                    int(self.grower_params.num_bins)):
                pack4_train = True
            else:
                log.warning(
                    "tpu_bin_pack4=true: training keeps u8 bin columns — "
                    "nibble packing needs every stored column to realize "
                    f"<= 16 bins and max_bin <= 15 (histogram width "
                    f"{int(self.grower_params.num_bins)}, widest column "
                    f"{nb_max})")
        layout = RowLayout(num_features=int(self.binned.shape[1]),
                           num_extra=e, packed4=pack4_train)
        self._cx_label = k + gcols
        self._cx_weight = k + gcols + 1 if has_w else None
        self._cx_rowid = e - 1
        gp = self.grower_params
        if pack4_train != gp.bin_pack4:
            gp = gp._replace(bin_pack4=pack4_train)
            self.grower_params = gp
        force_efb_fused = os.environ.get("LGBM_TPU_FORCE_FUSED_EFB", "") == "1"
        if os.environ.get("LGBM_TPU_FUSED_DUAL", "") == "0":
            gp = gp._replace(fused_dual=False)
            self.grower_params = gp
        if os.environ.get("LGBM_TPU_FUSED_HIST_DEBUG", ""):
            hd = os.environ["LGBM_TPU_FUSED_HIST_DEBUG"]
            log.warning(f"LGBM_TPU_FUSED_HIST_DEBUG={hd}: fused kernel "
                        "histogram work altered - results are INVALID "
                        "(timing bisect)")
            gp = gp._replace(fused_hist_debug=hd)
            self.grower_params = gp
        if gp.fused_block and gp.efb_virtual and gp.fused_dual \
                and not force_efb_fused:
            # HISTORY: through round 4 the dual-residency kernel faulted
            # the TPU worker on EFB-bundled deep trees (F=532 bundle
            # columns, bs=64, 255 leaves). Round 5's in-kernel DMA-base
            # clamps fixed the fault — the hardened dual path now trains
            # the repro shape to completion with leaf counts exactly
            # matching an independent re-routing (scripts/
            # check_leaf_counts.py) — but bundled data stays on the
            # copy-back variant (round-3 design, ~1/3 more DMA per split,
            # measured within noise of dual at this shape) for one more
            # round of soak. LGBM_TPU_FORCE_FUSED_EFB=1 opts into dual.
            log.info("EFB-bundled dataset: using the copy-back fused "
                     "kernel variant")
            gp = gp._replace(fused_dual=False)
            self.grower_params = gp
        # record-width context for the registry's scoped-VMEM clamp:
        # kept so reset_parameter can re-run the SAME clamp when a
        # mid-run config change re-resolves the engine knobs
        from ..engines import registry as engine_registry
        self._fused_clamp_ctx = {
            "num_cols": layout.num_cols,
            "num_features": layout.num_features,
            "num_bins": int(self.grower_params.num_bins),
        }
        if gp.fused_block:
            # kernel scoped-VMEM buffers scale with block_size * num_cols,
            # the batched-M pending ring with hist_mbatch * block_size,
            # and the histogram accumulator with num_cols * num_bins; the
            # registry-owned clamp scales the block down for wide records
            # / deep rings and falls back to the XLA walk when the
            # histogram alone would blow the ~16MB scoped limit
            resolved_bs = engine_registry.clamp_fused_block(
                gp.fused_block, layout.num_cols, gp.hist_mbatch,
                gp.hist_layout, num_bins=int(self.grower_params.num_bins),
                num_features=layout.num_features,
                env_override=os.environ.get("LGBM_TPU_FUSED_BS", ""))
            if resolved_bs != gp.fused_block:
                gp = gp._replace(fused_block=resolved_bs)
                self.grower_params = gp
        # the fused kernel's aligned block writes may overrun a segment end
        # by up to one block + one alignment tile
        pad = max(gp.part_block, gp.hist_block, gp.fused_block + 32)
        parts = [self.train_score]
        if gcols:
            parts.append(jnp.zeros((gcols, n), jnp.float32))
        parts.append(obj.label[None, :])
        if has_w:
            parts.append(obj.weight[None, :])
        parts.append(jnp.arange(n, dtype=jnp.float32)[None, :])
        extras = jnp.concatenate(parts, axis=0)
        zeros = jnp.zeros((n,), jnp.float32)
        # padded rows (mesh row-count alignment) start permanently out of
        # bag: zero count weight, zero gradients
        cnt0 = (np.asarray(self._valid_row_mask, np.float32)
                if getattr(self, "_valid_row_mask", None) is not None
                else jnp.ones((n,), jnp.float32))
        if self.mesh is not None:
            # per-shard layout: each shard's rows sit in a contiguous block
            # followed by its own `pad` overrun rows, so the per-shard
            # partition walks never touch a neighbour shard
            from ..parallel.mesh import row_sharding_2d
            S = len(self.mesh.devices.ravel())
            nl = n // S
            flat = pack_rows(self.binned, zeros, zeros,
                             jnp.asarray(cnt0, jnp.float32), extras, layout,
                             pad_rows=0)
            c = flat.shape[1]
            work = jnp.pad(flat.reshape(S, nl, c),
                           ((0, 0), (0, pad), (0, 0))).reshape(-1, c)
            work = jax.device_put(work, row_sharding_2d(self.mesh))
            shards = {"S": S, "nl": nl, "pad_rows": pad}
        else:
            work = pack_rows(self.binned, zeros, zeros,
                             jnp.asarray(cnt0, jnp.float32), extras, layout,
                             pad_rows=pad)
            shards = {"S": 1, "nl": n, "pad_rows": pad}
        self._compact = {
            "layout": layout,
            "work": work,
            "scratch": jnp.zeros_like(work),
            "step": None,
            "epoch": 0,        # bumped per grown tree; keys the perm cache
            "perm_epoch": -1,
            "perm": None,
            **shards,
        }

    def _rank_grads_fn(self):
        """Jitted: bounded objective gradients for non-row-elementwise
        objectives (lambdarank), returned in the compact grower's CURRENT
        permuted row order. One device scatter/gather pair by the carried
        row-id column — no host round trip (reference: the rank objective
        always sees original query-contiguous rows, rank_objective.hpp:25)."""
        c = self._compact
        if c.get("rank_grad_fn") is None:
            obj = self.objective
            layout = c["layout"]
            S, nl, pr = c["S"], c["nl"], c["pad_rows"]
            nm = self.num_data
            off = layout.extra_off + 4 * self._cx_rowid

            def fn(work, scores_cur):
                from ..ops.compact import _u8_to_f32
                rows = (work.reshape(S, nl + pr, -1)[:, :nl]
                        .reshape(S * nl, -1) if S > 1 else work[:nm])
                rid = _u8_to_f32(rows[:, off:off + 4]).astype(jnp.int32)
                s_orig = jnp.zeros_like(scores_cur).at[:, rid].set(scores_cur)
                g, h = obj.get_gradients(s_orig[0])
                return g[rid], h[rid]

            # position-bias objectives update host state (pos_biases) inside
            # get_gradients — run those eagerly, never under jit
            eager = (getattr(obj, "is_stochastic", False)
                     or getattr(obj, "positions", None) is not None)
            c["rank_grad_fn"] = fn if eager else jax.jit(fn)
        return c["rank_grad_fn"]

    def _compact_rows(self, work):
        """The row records in current order, per-shard pad rows stripped."""
        c = self._compact
        S, nl, pr = c["S"], c["nl"], c["pad_rows"]
        if S > 1:
            return work.reshape(S, nl + pr, -1)[:, :nl].reshape(S * nl, -1)
        return work[:self.num_data]

    def _compact_cols(self, work, *extra_idx):
        """Unpack selected extra f32 columns from the work array."""
        from ..ops.compact import _u8_to_f32
        layout = self._compact["layout"]
        rows = self._compact_rows(work)
        out = []
        for i in extra_idx:
            off = layout.extra_off + 4 * i
            out.append(_u8_to_f32(rows[:, off:off + 4]))
        return out

    def _build_compact_step_fn(self):
        """One fused jitted step per tree on the compact path: recompute
        gradients in the current row order, write the per-tree columns, grow
        (partitioning rows), renew/shrink leaves, and update scores — a
        single XLA program, zero host syncs. The work/scratch buffers are
        donated (updated in place)."""
        from jax import lax
        from ..ops.compact import _f32_to_u8, _u8_to_f32

        obj = self.objective
        renew = obj.renew_leaves
        layout = self._compact["layout"]
        gp = self.grower_params
        mesh = self.mesh
        if mesh is not None:
            from ..parallel.mesh import DATA_AXIS
            gp = gp._replace(axis_name=DATA_AXIS)
            # data-parallel histogram reduction: reduce-scatter over the
            # feature axis + tiny best-split all-gather instead of
            # all-reducing the full [F, B, 4] histogram (the reference's
            # actual protocol — ReduceScatter + SyncUpGlobalBestSplit,
            # data_parallel_tree_learner.cpp:223-300). EFB bundles and the
            # intermediate monotone method scan across features a shard
            # would not own, so they keep the all-reduce.
            sc_cfg = os.environ.get(
                "LGBM_TPU_HIST_SCATTER",
                str(self.config.get("tpu_hist_scatter", "auto"))).lower()
            n_sh = len(mesh.devices.ravel())
            sc_able = (n_sh > 1 and gp.efb_virtual == 0
                       and not gp.mono_intermediate)
            if sc_cfg in ("on", "1", "true") and not sc_able:
                why = ("a single-shard mesh has nothing to scatter"
                       if n_sh <= 1 else
                       "EFB bundles / monotone intermediate need "
                       "cross-feature histogram access")
                log.warning(f"tpu_hist_scatter=on: {why}; using the "
                            "full histogram all-reduce")
            if sc_cfg not in ("off", "0", "false") and sc_able:
                gp = gp._replace(hist_scatter=n_sh)
        k_total = self.num_tree_per_iteration
        # per-shard rows derive from the work buffer's SHAPE at trace
        # time (rows = work.shape[0] - the static block-overrun pad), not
        # from a baked closure int: the spmd flight check AOT-relowers
        # this same step at the full pod row count (aot_lower_program),
        # and every row-proportional quantity must follow the abstract
        # argument shapes
        pr = self._compact["pad_rows"]   # per-shard overrun pad (static)
        n_real_g = self._n_real
        rid_off = (self._compact["layout"].extra_off + 4 * self._cx_rowid)
        # rung-sized leaf arrays under the step ladder (see _build_step_fn)
        max_leaves = gp.num_leaves
        num_bins_arr = self.num_bins_arr
        nan_bin_arr = self.nan_bin_arr
        has_nan_arr = self.has_nan_arr
        is_cat_arr = self.is_cat_arr
        mono_types = self._mono_types
        inter_sets = self._inter_sets
        cegb_coupled = self._cegb_coupled
        use_cegb = self._use_cegb
        use_quant = self._use_quant
        quant_renew = use_quant and self._quant_renew
        if quant_renew and k_total > 1:
            # multiclass renewal needs iteration-start gradients, which are
            # not carried post-permutation; masked grower supports it
            log.warning("quant_train_renew_leaf with num_class>1 is only "
                        "supported by tpu_grower=masked; skipping renewal")
            quant_renew = False
        quant_bins = self._quant_bins
        quant_stoch = self._quant_stochastic
        # quantized-gradient INT histogram path (the int8 MXU speed lever):
        # grad/hess columns carry integer codes, histograms accumulate
        # int8 x int8 -> int32 and dequantize at the split scan. Requires
        # codes that survive the {0,1} bag multiply as integers — GOSS
        # amplifies sampled rows' gradients by a non-integer factor, and
        # multiclass carries per-class gradients whose shared scale would
        # need cross-step plumbing; both keep the dequantized-f32 shim.
        # Overflow bound: |hess code| <= quant_bins and the cross-shard
        # psum sums over GLOBAL rows, so a near-constant feature's root
        # bin holds up to num_data * quant_bins — that must stay inside
        # int32 (the per-shard 2^24 row cap alone does not bound the
        # reduced sums on many shards).
        quant_int = (use_quant and k_total == 1 and quant_bins <= 127
                     and self.num_data * quant_bins < (1 << 31)
                     and not isinstance(self.sample_strategy, GOSSStrategy))
        if use_quant and k_total == 1 and not quant_int \
                and self.num_data * quant_bins >= (1 << 31):
            log.warning(
                f"use_quantized_grad: num_data*num_grad_quant_bins = "
                f"{self.num_data}*{quant_bins} exceeds the int32 histogram "
                "range; using the dequantized-f32 histogram path")
        if quant_int:
            gp = gp._replace(quant_hist=True, quant_max=quant_bins + 1)
            # per-leaf bit-width narrowing (reference: GetHistBitsInLeaf,
            # gradient_discretizer.cpp — renewed as leaves shrink): leaves
            # whose code sums fit the packing radix take the packed-pair
            # engine at HALF the contraction work, selected per leaf by a
            # lax.cond in the compact grower (ops/grower_compact.py
            # seg_hist). It rides the XLA segment-histogram walk — the
            # fused Mosaic kernel histograms in-kernel on the int8 MXU
            # path, where s32 accumulation is native and narrowing buys
            # nothing.
            from ..ops.histogram import narrow_chunk_rows
            bits_cfg = int(self.config.get("tpu_quant_hist_bits", 0) or 0)
            if bits_cfg not in (0, 16, 32):
                log.warning(f"tpu_quant_hist_bits={bits_cfg} is not one of "
                            "0 (auto) | 16 | 32; using 32-bit accumulation")
                bits_cfg = 32
            narrow_able = (narrow_chunk_rows(quant_bins + 1) > 0
                           and gp.fused_block == 0)
            if bits_cfg == 16 and not narrow_able:
                log.warning(
                    "tpu_quant_hist_bits=16 needs the XLA segment-"
                    "histogram walk (tpu_fused=off) and a "
                    "num_grad_quant_bins small enough for the packing "
                    "radix; keeping 32-bit accumulation")
            if bits_cfg == 16 and narrow_able:
                gp = gp._replace(quant_narrow=True)
            # auto (bits_cfg == 0) stays on the int8 -> int32 engine: the
            # packed-pair engine's exactness radix caps its row chunks at
            # narrow_chunk_rows (a few hundred), and the measured CPU
            # sweep (BENCH_SHAPES layout_sweep) shows the chunking
            # overhead eats the halved channel count at B <= 64 while
            # int8 already beats the f32 einsum outright. Narrow is the
            # measured opt-in until a backend's sweep row says otherwise.
        self._quant_narrow_active = bool(quant_int and gp.quant_narrow)
        const_hess = bool(getattr(obj, "is_constant_hessian", False))
        feature_contri = self._feature_contri
        efb = self._efb
        sc_off = layout.extra_off            # K score columns live first
        lbl_off = layout.extra_off + 4 * self._cx_label
        w_off = (layout.extra_off + 4 * self._cx_weight
                 if self._cx_weight is not None else None)

        def col(work, off):                  # [n] f32 from 4 u8 columns
            return _u8_to_f32(work[:work.shape[0] - pr, off:off + 4])

        def scores_of(work):                 # [K, n] f32
            nn = work.shape[0] - pr
            raw = work[:nn, sc_off:sc_off + 4 * k_total]
            return _u8_to_f32(raw.reshape(nn, k_total, 4)).T

        gx_off = (layout.extra_off + 4 * self._cx_grads
                  if self._cx_grads is not None else None)

        ext_grads = getattr(self, "_ext_grads", False)

        def step(work, scratch, scores, bag_w, use_stored_bag, feat_mask,
                 shrinkage, bynode_key, cegb_used, quant_key, extra_key,
                 leaf_budget, depth_budget, ext_g=None, ext_h=None, *, k):
            n = work.shape[0] - pr           # per-shard rows (trace-static)
            pad_n = pr

            w_col = jnp.where(use_stored_bag, col(work, layout.cnt_off),
                              bag_w)
            if mesh is not None and self.num_data > n_real_g:
                # mesh row-count padding: pad rows (row id >= n_real) must
                # stay permanently out of bag even when a fresh bag draws
                # them — their label/score bytes are meaningless
                w_col = w_col * (col(work, rid_off) < n_real_g)
            label = col(work, lbl_off)
            weight = col(work, w_off) if w_off is not None else None
            class_grads = []
            quant_scales = None
            if ext_grads:
                # gradients arrive pre-computed in the CURRENT row order
                # (lambdarank couples rows of a query; _rank_grads_fn)
                g_k, h_k = ext_g, ext_h
            elif k_total == 1:
                g, h = _bound_gradients(obj, k_total, scores, label, weight)
                if quant_int:
                    # integer-code path: the grad/hess columns carry the
                    # discretizer CODES (exact small ints in f32 lanes) and
                    # the per-iteration scales flow to the split scan as
                    # traced scalars — the histogram pipeline runs
                    # int8 x int8 -> int32 end to end
                    qk = quant_key
                    if gp.axis_name is not None:
                        # shard-independent stochastic rounding draws
                        qk = jax.random.fold_in(
                            qk, lax.axis_index(gp.axis_name))
                    qg, qh, g_s, h_s = _discretize_gradients(
                        g, h, qk, quant_bins, quant_stoch, const_hess,
                        axis_name=gp.axis_name)
                    g, h = qg, qh
                    quant_scales = (g_s, h_s)
                elif use_quant:
                    g, h = _quantize_gradients(
                        g, h, quant_key, quant_bins, quant_stoch, const_hess)
                g_k, h_k = g[0], h[0]
            elif k == 0:
                # all K class gradients once per iteration, from the
                # iteration-start scores (reference: GBDT::Boosting runs
                # before the per-class tree loop, gbdt.cpp:220); stored in
                # carried columns so later trees see them permutation-aligned
                g, h = _bound_gradients(obj, k_total, scores, label, weight)
                if use_quant:
                    g, h = _quantize_gradients(
                        g, h, quant_key, quant_bins, quant_stoch, const_hess)
                g_k, h_k = g[0], h[0]
                class_grads = ([g[j] for j in range(k_total)]
                               + [h[j] for j in range(k_total)])
            else:
                g_k = col(work, gx_off + 4 * k)
                h_k = col(work, gx_off + 4 * (k_total + k))
            # grad/hess/cnt, the K score columns, and (at k=0) the per-class
            # gradient columns are CONTIGUOUS lanes — write them in ONE
            # update (4 separate lane-slice updates cost ~27 ms each at 10.5M
            # rows; one fused update costs the same as one of them)
            cols = [g_k * w_col, h_k * w_col, w_col]
            # scores are authoritative outside the work array; write all K
            # columns fresh so they ride the partition correctly
            cols += [scores[j] for j in range(k_total)]
            cols += class_grads
            packed = jnp.concatenate(
                [_f32_to_u8(jnp.pad(v, (0, pad_n))) for v in cols], axis=1)
            work = work.at[:, layout.grad_off:
                           layout.grad_off + 4 * len(cols)].set(packed)

            (tree, row_leaf, work, scratch, leaf_start,
             leaf_nrows) = grow_tree_compact(
                work, scratch, num_bins_arr, nan_bin_arr, has_nan_arr,
                is_cat_arr, feat_mask, layout, gp, n,
                mono_types, inter_sets, bynode_key, cegb_coupled, cegb_used,
                extra_key, feature_contri, efb, quant_scales=quant_scales,
                leaf_budget=leaf_budget, depth_budget=depth_budget)
            if use_cegb:
                cegb_used = _tree_used_features(tree, layout.num_features,
                                                cegb_used)

            leaf_value = tree.leaf_value
            if renew:
                residual = col(work, lbl_off) - scores_of(work)[k]
                wts = (col(work, layout.cnt_off) != 0.0).astype(jnp.float32)
                if w_off is not None:
                    wts = wts * col(work, w_off)
                renewed = renew_leaf_quantile(
                    residual, wts, row_leaf, max_leaves,
                    float(obj.renew_alpha))
                live = jnp.arange(max_leaves) < tree.num_leaves
                leaf_value = jnp.where(live, renewed, leaf_value)

            if quant_renew:
                # TRUE gradients from carried label/score columns, summed
                # per contiguous leaf segment via cumsum differences
                # (reference: RenewIntGradTreeOutput)
                tg, th = _bound_gradients(
                    obj, k_total, scores_of(work),
                    col(work, lbl_off),
                    col(work, w_off) if w_off is not None else None)
                wq = col(work, layout.cnt_off)
                tgk = tg[k] * wq
                thk = th[k] * wq
                csg = jnp.concatenate([jnp.zeros(1), jnp.cumsum(tgk)])
                csh = jnp.concatenate([jnp.zeros(1), jnp.cumsum(thk)])
                ends = jnp.minimum(leaf_start + leaf_nrows, n)
                sums_g = csg[ends] - csg[jnp.minimum(leaf_start, n)]
                sums_h = csh[ends] - csh[jnp.minimum(leaf_start, n)]
                if mesh is not None:
                    from ..parallel.mesh import DATA_AXIS
                    sums_g = jax.lax.psum(sums_g, DATA_AXIS)
                    sums_h = jax.lax.psum(sums_h, DATA_AXIS)
                from ..ops.split import leaf_output as _lo
                live = jnp.arange(max_leaves) < tree.num_leaves
                leaf_value = jnp.where(
                    live, _lo(sums_g, sums_h, gp.split_params()), leaf_value)
            lv = jnp.where(tree.num_nodes > 0, leaf_value, 0.0) * shrinkage
            tree = tree._replace(
                leaf_value=lv,
                internal_value=tree.internal_value * shrinkage)
            _, row_delta = segments_to_leaf_vectors(
                leaf_start, leaf_nrows, lv, n)
            sc = scores_of(work).at[k].add(row_delta)
            return tree, work, scratch, sc, cegb_used

        if mesh is None:
            jitted = jax.jit(step, donate_argnums=(0, 1),
                             static_argnames=("k",))
            if os.environ.get("LGBM_TPU_COMM_ACCOUNTING", "") == "1":
                # same key scheme as the mesh dispatch below so hlo_check
                # addresses the serial/compact step uniformly
                return self._comm_capture(
                    jitted, lambda kw: f"compact_step_k{kw.get('k', 0)}")
            return jitted

        # data-parallel: the whole per-tree step runs per shard under
        # shard_map — shard-local partitions, psum-ed histograms inside
        # grow_tree_compact. Trees replicate bit-identically because every
        # shard scans the same psum-ed histograms (reference: all ranks apply
        # the same SyncUpGlobalBestSplit decision, parallel_tree_learner.h)
        from jax.sharding import PartitionSpec as P
        from ..parallel.mesh import DATA_AXIS
        try:
            from jax import shard_map as _shard_map

            def smap(f, in_specs, out_specs):
                return _shard_map(f, mesh=mesh, in_specs=in_specs,
                                  out_specs=out_specs, check_vma=False)
        except ImportError:  # pragma: no cover
            from jax.experimental.shard_map import shard_map as _shard_map

            def smap(f, in_specs, out_specs):
                return _shard_map(f, mesh=mesh, in_specs=in_specs,
                                  out_specs=out_specs, check_rep=False)

        row2 = P(DATA_AXIS, None)
        krow = P(None, DATA_AXIS)
        rep = P()
        in_specs = (row2, row2, krow, P(DATA_AXIS), rep, rep, rep, rep,
                    rep, rep, rep, rep, rep)
        if ext_grads:
            in_specs = in_specs + (P(DATA_AXIS), P(DATA_AXIS))
        # outputs: (tree pytree — replicated, work, scratch, scores,
        # cegb_used); specs are pytree prefixes
        out_specs = (rep, row2, row2, krow, rep)
        fns = {}

        def dispatch(*args, k):
            if k not in fns:
                jitted = jax.jit(
                    smap(functools.partial(step, k=k), in_specs, out_specs),
                    donate_argnums=(0, 1))
                if os.environ.get("LGBM_TPU_COMM_ACCOUNTING", "") == "1":
                    jitted = self._comm_capture(jitted, f"compact_step_k{k}")
                fns[k] = jitted
            return fns[k](*args)

        return dispatch

    def _compact_perm(self) -> np.ndarray:
        """Current row permutation (original index per position), cached per
        grown tree — used to reorder host-side metric arrays."""
        c = self._compact
        if c["perm_epoch"] != c["epoch"]:
            (rid,) = self._compact_cols(c["work"], self._cx_rowid)
            c["perm"] = np.asarray(rid).astype(np.int64)
            c["perm_epoch"] = c["epoch"]
        return c["perm"]

    def _cegb_state(self) -> jax.Array:
        if self._cegb_used is None:
            self._cegb_used = jnp.zeros(
                (int(self.binned.shape[1])
                 + self.grower_params.efb_virtual,), bool)
        return self._cegb_used

    def _cegb_charged_state(self) -> jax.Array:
        """Lazy-penalty charged-rows bitmap, persisted across the whole
        model (reference: feature_used_in_data_ is filled once and never
        reset, cost_effective_gradient_boosting.hpp:62)."""
        if self._cegb_charged is None:
            f = int(self.binned.shape[1])
            n = (int(self.binned.shape[0])
                 if self._cegb_lazy is not None else 1)
            fdim = f if self._cegb_lazy is not None else 1
            self._cegb_charged = jnp.zeros((fdim, n), bool)
        return self._cegb_charged

    def _compact_gradients(self):
        """Gradients in the current (permuted) row order, for GOSS ranking."""
        c = self._compact
        if c.get("grad_fn") is None:
            obj = self.objective
            k_total = self.num_tree_per_iteration

            def fn(scores, label, weight):
                return _bound_gradients(obj, k_total, scores, label, weight)

            c["grad_fn"] = jax.jit(fn) \
                if not getattr(self.objective, "is_stochastic", False) else fn
        label, = self._compact_cols(c["work"], self._cx_label)
        weight = (self._compact_cols(c["work"], self._cx_weight)[0]
                  if self._cx_weight is not None else None)
        return c["grad_fn"](self.train_score, label, weight)

    def _train_one_iter_compact(self) -> bool:
        """Compact-path iteration (same contract as train_one_iter)."""
        self._boost_from_average()
        c = self._compact
        if c["step"] is None:
            c["step"] = self._build_compact_step_fn()
        strat = self.sample_strategy
        n = self.num_data      # bag vectors align with work rows (incl. pad)

        # GOSS ranks rows by gradient magnitude; compute in current order
        g = h = None
        if strat.is_hessian_change:
            g, h = self._compact_gradients()
        mask = strat.bag_mask(self.iter_, g, h)
        # fresh == the strategy actually drew a new bag this iteration; a
        # reused (cached) bag must come from the stored sample-weight column,
        # which rode the partitions and is in the current row order — the
        # host-cached vector is not
        fresh = getattr(strat, "last_fresh", mask is not None)
        if mask is None:
            mask = jnp.ones((n,), jnp.float32)
            fresh = self.iter_ == 0 or fresh
        if getattr(strat, "_amplify", None) is not None:
            mask = mask * strat._amplify

        feat_mask = self._feature_mask()
        first_iter = self.num_total_trees < self.num_tree_per_iteration
        k_total = self.num_tree_per_iteration
        ext_args = ()
        if getattr(self, "_ext_grads", False):
            # lambdarank-style coupled gradients: computed once per
            # iteration in original query order, permuted to current order
            ext_args = tuple(self._rank_grads_fn()(
                c["work"], self.train_score))
        for k in range(k_total):
            # trees after the first in an iteration reuse the stored bag
            # (same bag for all trees of one iteration, like the reference)
            use_stored = not (fresh and k == 0)
            (tree, work, scratch, scores,
             self._cegb_used) = c["step"](
                c["work"], c["scratch"], self.train_score, mask,
                jnp.asarray(use_stored), feat_mask,
                jnp.float32(self.shrinkage_rate),
                jax.random.fold_in(self._bynode_key, self.num_total_trees),
                self._cegb_state(),
                jax.random.fold_in(self._quant_key, self.iter_),
                jax.random.fold_in(self._extra_key, self.num_total_trees),
                *self._step_budget_args(), *ext_args, k=k)
            c["work"], c["scratch"] = work, scratch
            c["epoch"] += 1
            self.train_score = scores
            self._update_valid_scores(tree, k)
            if first_iter and abs(self._init_scores[k]) > 1e-10:
                tree = tree._replace(
                    leaf_value=tree.leaf_value + self._init_scores[k])
            self._dev_trees.append((tree, self.shrinkage_rate))
            # NOTE: appends do NOT invalidate the device-tree cache — the
            # bucketed cache append-pads new trees in (mid-train predict
            # used to re-stack the whole model every iteration)

        self.iter_ += 1
        if len(self._dev_trees) >= k_total * self.stop_check_freq:
            return self._flush_trees()
        return False

    def add_valid(self, valid_set: BinnedDataset, name: str,
                  metrics: Sequence[Metric]) -> None:
        # the valid matrix must be in the SAME column space the booster
        # routes in: a bundle-layout mismatch (e.g. the valid rows hit a
        # feature conflict and stayed dense, or the train side unbundled)
        # would silently corrupt validation scores
        vb = getattr(valid_set, "bundle_info", None)
        if self._efb is not None:
            if vb is None or (valid_set.binned.shape[1]
                              != int(self.binned.shape[1])):
                raise ValueError(
                    f"validation set '{name}' is not in the training data's "
                    "EFB bundle layout (a feature conflict outside the "
                    "training rows?); rebuild both with enable_bundle=false")
        elif vb is not None:
            from ..io.efb import unbundle
            log.warning(f"validation set '{name}': unbundling to match the "
                        "unbundled training layout")
            dbins = np.array([m.default_bin for m in valid_set.mappers],
                             np.int32)
            valid_set.binned = unbundle(
                np.asarray(valid_set.binned), vb, dbins,
                valid_set.feature_num_bins())
            valid_set.bundle_info = None
        vs = _ValidSet(valid_set, self.num_tree_per_iteration, name,
                       mesh=self.mesh if self.tree_learner != "feature"
                       else None)
        if self._linear and valid_set.raw_data is None:
            raise ValueError(
                "linear_tree validation sets need raw data; create them "
                "from the training Dataset (create_valid) with "
                "free_raw_data=False or the linear_tree param set")
        for m in metrics:
            m.init(valid_set.metadata, valid_set.num_data)
        vs.metrics = list(metrics)
        self.valid_sets.append(vs)

    def set_train_metrics(self, metrics: Sequence[Metric]) -> None:
        for m in metrics:
            # multi-host: metrics need the GLOBAL gathered metadata
            m.init(getattr(self, "_global_md", None)
                   or self.train_set.metadata, self._n_real)
        self.train_metrics = list(metrics)

    # -- one boosting iteration ---------------------------------------------
    def _boost_from_average(self) -> None:
        """(reference: GBDT::BoostFromAverage, gbdt.cpp:319)"""
        if self.num_total_trees == 0 and not self._has_init_score \
                and self.objective is not None \
                and bool(self.config.get("boost_from_average", True)):
            for k in range(self.num_tree_per_iteration):
                init = self.objective.boost_from_score(k)
                if abs(init) > 1e-10:
                    self._init_scores[k] = init
                    self.train_score = self.train_score.at[k].add(init)
                    for vs in self.valid_sets:
                        vs.score = vs.score.at[k].add(init)
                    log.info(f"Start training from score {init:.6f}")

    def _gradients(self) -> Tuple[jax.Array, jax.Array]:
        """(reference: GBDT::Boosting, gbdt.cpp:220)"""
        from ..obs.spans import span
        if self._grad_fn is None:
            base = self.objective.get_gradients

            def named(*a, **kw):
                # span at trace time: the gradient program carries its
                # phase name into the device trace
                with span("gradient"):
                    return base(*a, **kw)

            fn = named
            if not getattr(self.objective, "is_stochastic", False):
                fn = jax.jit(named)
            self._grad_fn = fn
        score = self.train_score
        if self.num_tree_per_iteration == 1:
            g, h = self._grad_fn(score[0])
            return g[None, :], h[None, :]
        return self._grad_fn(score)

    def _efb_precheck(self, train_set, cfg, tree_learner) -> None:
        """Unbundle an EFB dataset when this configuration won't use the
        bundle-space compact grower (mirrors the can_compact conditions in
        _setup_train plus the bundle-incompatible knobs). Runs BEFORE device
        placement so every learner sees a plain dense matrix."""
        binfo = getattr(train_set, "bundle_info", None)
        if binfo is None:
            return
        obj = self.objective
        grower = str(cfg.get("tpu_grower", "auto")).lower()
        compact_possible = (
            tree_learner in ("serial", "data")
            and not self._multiproc
            and obj is not None
            and getattr(obj, "row_elementwise", True)
            and not getattr(obj, "is_stochastic", False)
            and int(train_set.max_num_bins) <= 256
            and float(cfg.get("pos_bagging_fraction", 1.0)) >= 1.0
            and float(cfg.get("neg_bagging_fraction", 1.0)) >= 1.0
            and not bool(cfg.get("bagging_by_query", False))
            and train_set.metadata.query_boundaries is None
            and not bool(cfg.get("linear_tree", False))
            and not str(cfg.get("forcedsplits_filename", "") or "")
            and grower != "masked"
            # a bundled dataset always routes to the compact grower under
            # grower=auto (see _setup_train), at any row count
            and grower in ("compact", "auto")
            and not (self.mesh is not None and obj.renew_leaves))
        knobs_ok = (
            cfg.get("monotone_constraints") is None
            and cfg.get("interaction_constraints") is None
            and cfg.get("feature_contri") is None
            and float(cfg.get("cegb_penalty_split", 0) or 0) == 0.0
            and cfg.get("cegb_penalty_feature_coupled") is None
            and (cfg.get("cegb_penalty_feature_lazy") is None
                 or not self._supports_lazy_cegb))
        if compact_possible and knobs_ok:
            return
        log.warning(
            "EFB bundles are not supported by this configuration; "
            "unbundling the dataset (set enable_bundle=false to skip "
            "bundling entirely)")
        from ..io.efb import unbundle
        dbins = np.array([m.default_bin for m in train_set.mappers],
                         np.int32)
        train_set.binned = unbundle(
            np.asarray(train_set.binned), binfo, dbins,
            train_set.feature_num_bins())
        train_set.bundle_info = None

    def _setup_efb(self, train_set: BinnedDataset) -> None:
        """Wire an EFB-bundled dataset (io/efb.py) into the learner.

        Scan space = stored columns + one VIRTUAL feature per bundled
        original (its histogram is synthesized from its bundle column's bin
        range, ops/split.py extend_hist_efb); routing space = stored columns
        (bundled splits carry a ready bitset). Tree arrays record ORIGINAL
        feature ids, so model text and raw-data prediction never see bundles
        (reference analogue: FeatureGroup keeps group bins while SplitInfo
        carries the real feature, include/LightGBM/feature_group.h)."""
        self._efb = None
        binfo = getattr(train_set, "bundle_info", None)
        if binfo is None:
            return
        if self.mesh is not None and self.tree_learner not in ("data",):
            raise ValueError(
                "EFB-bundled datasets support the serial and data-parallel "
                "learners; construct the Dataset with enable_bundle=false "
                f"for tree_learner={self.tree_learner}")
        bad = [name for flag, name in (
            (self._mono_types is not None, "monotone_constraints"),
            (self._inter_sets is not None, "interaction_constraints"),
            (self._use_cegb, "cegb penalties"),
            (self._feature_contri is not None, "feature_contri"),
            (self._forced_splits is not None, "forcedsplits"),
            (self._linear, "linear_tree"),
        ) if flag]
        if bad or not self._use_compact:
            # graceful fallback: bundling is lossless, so reconstruct the
            # dense binned matrix and train unbundled (reference analogue:
            # EFB is construction-time there too, but its learners all read
            # FeatureGroups; ours only the compact grower does)
            why = ", ".join(bad) if bad else "the masked grower"
            log.warning(f"EFB bundles are not supported with {why}; "
                        "unbundling the dataset (set enable_bundle=false to "
                        "skip bundling entirely)")
            from ..io.efb import unbundle
            dbins = np.array([m.default_bin for m in train_set.mappers],
                             np.int32)
            dense = unbundle(np.asarray(train_set.binned), binfo, dbins,
                             train_set.feature_num_bins())
            train_set.binned = dense
            train_set.bundle_info = None
            # rebuild the device matrix exactly as _setup_train placed it
            if self._pad:
                dense = np.pad(dense, ((0, self._pad), (0, 0)))
            if self.mesh is not None:
                from ..parallel.mesh import row_sharding_2d
                if self._multiproc:
                    self.binned = jax.make_array_from_process_local_data(
                        row_sharding_2d(self.mesh), dense)
                else:
                    self.binned = jax.device_put(dense,
                                                 row_sharding_2d(self.mesh))
            else:
                self.binned = jnp.asarray(dense)
            return
        C = binfo.n_columns
        mappers = train_set.mappers
        orig_nb = train_set.feature_num_bins()
        orig_nan = train_set.feature_nan_bins()
        orig_cat = train_set.feature_is_categorical()
        orig_has_nan = np.array(
            [m.missing_type == 2 and not m.is_categorical for m in mappers],
            bool)
        orig_dbin = np.array([m.default_bin for m in mappers], np.int32)
        nontrivial = np.array([not m.is_trivial for m in mappers], bool)
        bundled = np.nonzero(binfo.offset_of >= 0)[0]
        passthrough = np.nonzero(binfo.offset_of < 0)[0]
        Fb = len(bundled)

        def colv(vals, fill):
            vals = np.asarray(vals)
            v = np.full(C, fill, vals.dtype)
            v[binfo.col_of[passthrough]] = vals[passthrough]
            return v

        self.num_bins_arr = jnp.asarray(np.concatenate(
            [binfo.num_column_bins, orig_nb[bundled]]).astype(np.int32))
        self.nan_bin_arr = jnp.asarray(np.concatenate(
            [colv(orig_nan, 0), orig_nan[bundled]]).astype(np.int32))
        self.has_nan_arr = jnp.asarray(np.concatenate(
            [colv(orig_has_nan, False), np.zeros(Fb, bool)]))
        self.is_cat_arr = jnp.asarray(np.concatenate(
            [colv(orig_cat, False), np.zeros(Fb, bool)]))
        # bundle columns themselves never win a split
        self.base_feat_mask = np.concatenate(
            [colv(nontrivial, False), np.ones(Fb, bool)])
        orig_of_col = np.full(C, -1, np.int32)
        orig_of_col[binfo.col_of[passthrough]] = passthrough
        self._efb = tuple(jnp.asarray(a) for a in (
            np.concatenate([np.arange(C, dtype=np.int32),
                            binfo.col_of[bundled]]),          # col_of_ext
            np.concatenate([colv(orig_cat, False),
                            np.ones(Fb, bool)]),              # route_cat_ext
            np.concatenate([np.full(C, -1, np.int32),
                            binfo.offset_of[bundled]]),       # off_ext
            np.concatenate([np.zeros(C, np.int32),
                            orig_nb[bundled]]),               # nb_ext
            np.concatenate([np.zeros(C, np.int32),
                            orig_dbin[bundled]]),             # dbin_ext
            np.concatenate([orig_of_col,
                            bundled.astype(np.int32)]),       # orig_of_ext
        ))
        # per-ORIGINAL routing (valid scoring / DART / rollback replay)
        # and plain per-original arrays for prediction (prediction rows are
        # binned per ORIGINAL feature, never bundled)
        self._orig_nan_arr = jnp.asarray(orig_nan.astype(np.int32))
        self._orig_cat_arr = jnp.asarray(orig_cat)
        self._route_nan = self._orig_nan_arr
        self._route_cat = jnp.asarray(orig_cat | (binfo.offset_of >= 0))
        self._route_col = jnp.asarray(binfo.col_of.astype(np.int32))
        self._num_orig_features = train_set.num_total_features
        self.grower_params = self.grower_params._replace(
            efb_virtual=Fb, efb_bmax=int(orig_nb[bundled].max()))

    def _route_args(self):
        """(nan_bin, is_cat[, col_of]) arrays for route_one_tree."""
        if self._efb is not None:
            return (self._route_nan, self._route_cat, self._route_col)
        return (self.nan_bin_arr, self.is_cat_arr)

    def _feature_mask(self) -> jnp.ndarray:
        """Per-tree column sampling (reference: ColSampler, col_sampler.hpp)."""
        mask = self.base_feat_mask.copy()
        if self.feature_fraction < 1.0:
            used = np.where(mask)[0]
            keep = max(1, int(np.ceil(len(used) * self.feature_fraction)))
            chosen = self._feat_rng.choice(used, size=keep, replace=False)
            mask = np.zeros_like(mask)
            mask[chosen] = True
        return jnp.asarray(mask)

    def train_one_iter(
        self,
        gradients: Optional[np.ndarray] = None,
        hessians: Optional[np.ndarray] = None,
    ) -> bool:
        """Train trees for one iteration; True when training should stop
        (reference: GBDT::TrainOneIter, gbdt.cpp:344)."""
        k, n = self.num_tree_per_iteration, self.num_data
        if self._use_compact:
            if gradients is not None or hessians is not None:
                if self._compact is not None:
                    raise RuntimeError(
                        "cannot switch to caller-supplied gradients after "
                        "compact training started; set tpu_grower=masked")
                # caller-supplied gradients arrive in the original row order
                self._use_compact = False
            else:
                if self._compact is None:
                    self._setup_compact_state()
                return self._train_one_iter_compact()
        if gradients is None or hessians is None:
            self._boost_from_average()
            grad, hess = self._gradients()
        else:
            g_np = np.asarray(gradients, np.float32).reshape(k, self._n_real)
            h_np = np.asarray(hessians, np.float32).reshape(k, self._n_real)
            if self._pad:
                g_np = np.pad(g_np, ((0, 0), (0, self._pad)))
                h_np = np.pad(h_np, ((0, 0), (0, self._pad)))
            grad, hess = jnp.asarray(g_np), jnp.asarray(h_np)

        if self._valid_row_mask is not None:
            # zero padding-row gradients before GOSS ranks them
            grad = grad * self._valid_row_mask[None, :]
            hess = hess * self._valid_row_mask[None, :]
        mask = self.sample_strategy.bag_mask(self.iter_, grad, hess)
        grad, hess = self.sample_strategy.scale_grad_hess(mask, grad, hess)
        if mask is None:
            mask = jnp.ones((n,), jnp.float32)
        if self._valid_row_mask is not None:
            mask = mask * self._valid_row_mask

        feat_mask = self._feature_mask()
        first_iter = self.num_total_trees < self.num_tree_per_iteration
        if self._step_fn is None:
            self._step_fn = self._build_step_fn()
        true_grad, true_hess = grad, hess
        if self._use_quant:
            # one global-scale quantization per iteration over all classes
            # (reference: DiscretizeGradients on the full k*N buffer)
            grad, hess = _quantize_gradients(
                grad, hess,
                jax.random.fold_in(self._quant_key, self.iter_),
                self._quant_bins, self._quant_stochastic,
                bool(getattr(self.objective, "is_constant_hessian", False)))

        for cur_tree_id in range(k):
            (tree, row_leaf, new_score, self._cegb_used,
             self._cegb_charged) = self._step_fn(
                self.binned,
                self.train_score[cur_tree_id], grad[cur_tree_id],
                hess[cur_tree_id], mask, feat_mask,
                jnp.float32(self.shrinkage_rate),
                jax.random.fold_in(self._bynode_key, self.num_total_trees),
                self._cegb_state(),
                true_grad[cur_tree_id], true_hess[cur_tree_id],
                jax.random.fold_in(self._extra_key, self.num_total_trees),
                self._cegb_charged_state(), *self._step_budget_args())
            if self._linear:
                split_ok = self._linear_tree_iter(
                    tree, row_leaf, true_grad[cur_tree_id],
                    true_hess[cur_tree_id], mask, cur_tree_id, first_iter)
                self._linear_any_split = (
                    getattr(self, "_linear_any_split", False) or split_ok)
                continue
            self.train_score = self.train_score.at[cur_tree_id].set(new_score)
            # valid scores got the init at _boost_from_average already, so the
            # tree must be pushed through them BEFORE the bias fold
            self._update_valid_scores(tree, cur_tree_id)
            if first_iter and abs(self._init_scores[cur_tree_id]) > 1e-10:
                # fold the init score into the first tree's leaves, on device
                # (reference: Tree::AddBias, gbdt.cpp:417; also covers the
                # constant first tree, AsConstantTree(init), gbdt.cpp:430)
                tree = tree._replace(
                    leaf_value=tree.leaf_value + self._init_scores[cur_tree_id])
            self._dev_trees.append((tree, self.shrinkage_rate))

        self.iter_ += 1
        if self._linear:
            # all-constant iteration ends training (reference gbdt.cpp:440)
            if not getattr(self, "_linear_any_split", False):
                # same accounting as _flush_trees (reference gbdt.cpp:440):
                # pop the failed iteration unless it is the very first
                if len(self.models) > k:
                    del self.models[-k:]
                    # removal, not append: a cached stack may hold the
                    # popped trees (append-pad cannot repair deletions)
                    self._invalidate_device_trees()
                self.iter_ -= 1
                log.warning("Stopped training because there are no more "
                            "leaves that meet the split requirements")
                return True
            self._linear_any_split = False
            return False
        # stop-check + host materialization, batched to bound device->host
        # round trips (reference checks every iter, gbdt.cpp:440; one sync per
        # `stop_check_freq` iters here — the tunneled-TPU RTT is ~130ms)
        if len(self._dev_trees) >= k * self.stop_check_freq:
            return self._flush_trees()
        return False

    def _obs_iteration_tick(self, seconds: float) -> None:
        """Per-update telemetry tick (called from Booster.update): one
        flight-ring event and, when ``tpu_metrics_path`` is armed, one
        JSONL record carrying CUMULATIVE phase-keyed compile counts and
        persistent-cache counters — host-only reads (python ints and the
        wall clock), so the steady-state 0-d2h guard holds with telemetry
        fully enabled. ``iteration`` is the count of completed updates
        (absolute, so resumed runs line up)."""
        from ..analysis import guards
        from ..obs import flight
        flight.note("iteration", iteration=self.iter_,
                    seconds=round(seconds, 6))
        stream = getattr(self, "_metrics_stream", None)
        if stream is not None:
            stream.emit("iteration", iteration=self.iter_,
                        seconds=round(seconds, 6),
                        compiles=guards.phase_compile_counts(),
                        cache=guards.global_cache_counts())

    def train_metrics_tree(self) -> Dict[str, Any]:
        """The live training-metrics tree the in-train Prometheus
        endpoint (``tpu_metrics_port`` under ``lgb.train``) serves:
        iteration progress, phase-keyed compile counters, persistent-
        cache counters, and the latest rank-stats aggregate (median /
        p99 / max over ranks, straggler flags) when sampling is armed.
        Host-only reads — scraping must not touch the device."""
        from ..analysis import guards
        tree = {
            "training": True,
            "iteration": self.iter_,
            "compiles": guards.phase_compile_counts(),
            "cache": guards.global_cache_counts(),
        }
        rs = getattr(self, "_rank_stats", None)
        if rs is not None:
            tree["rank_stats"] = rs.latest_tree()
        return tree

    def _linear_tree_iter(self, tree, row_leaf, grad_k, hess_k, mask,
                          cur_tree_id: int, first_iter: bool) -> None:
        """Host-orchestrated linear-leaf fitting + score updates for one tree
        (reference: LinearTreeLearner::CalculateLinear; CPU-only there too)."""
        from .linear import (add_bias_linear, fit_linear_leaves,
                             linear_leaf_outputs)
        host = HostTree(jax.device_get(tree), shrinkage=self.shrinkage_rate)
        if host.num_nodes == 0:
            host.num_leaves = 1
        raw = self.train_set.raw_data
        leaf_np = np.asarray(row_leaf)
        g_np = np.asarray(grad_k * mask)
        h_np = np.asarray(hess_k * mask)
        is_cat = np.asarray(self.is_cat_arr)
        fit_linear_leaves(host, raw, leaf_np, g_np, h_np, is_cat,
                          float(self.config.get("linear_lambda", 0.0)),
                          shrinkage=self.shrinkage_rate)
        delta = linear_leaf_outputs(host, raw, leaf_np)
        self.train_score = self.train_score.at[cur_tree_id].add(
            jnp.asarray(delta, jnp.float32))
        for vs in self.valid_sets:
            vleaf = route_one_tree(
                vs.binned, tree.split_feature, tree.split_bin,
                tree.cat_bitset, tree.default_left, tree.left_child,
                tree.right_child, tree.num_nodes, *self._route_args())
            vdelta = linear_leaf_outputs(
                host, vs.dataset.raw_data, np.asarray(vleaf)[: vs.n_real])
            vs.score = vs.score.at[cur_tree_id, : vs.n_real].add(
                jnp.asarray(vdelta, jnp.float32))
        if first_iter and abs(self._init_scores[cur_tree_id]) > 1e-10:
            init = self._init_scores[cur_tree_id]
            host.leaf_value = host.leaf_value + init
            add_bias_linear(host, init)
        self.models.append(host)
        return host.num_nodes > 0

    @property
    def num_total_trees(self) -> int:
        # under the trees mutex so a read-locked num_trees()/
        # current_iteration() never observes a mid-flush torn count
        # (a concurrent read-locked predict may be flushing)
        with self._trees_mu:
            return len(self.models) + len(self._dev_trees)

    def _flush_trees(self) -> bool:
        """Materialize pending device trees to host in one batched transfer;
        returns True if training should stop (an iteration produced no
        splittable leaf — reference: gbdt.cpp:440-450)."""
        with self._trees_mu:
            return self._flush_trees_locked()

    def _flush_trees_locked(self) -> bool:
        if not self._dev_trees:
            return False
        k = self.num_tree_per_iteration
        trees = [t for t, _ in self._dev_trees]
        shrinks = [s for _, s in self._dev_trees]
        # one batched device_get of all pending trees; deliberately NOT a
        # jnp.stack program — its shape would depend on the pending count and
        # recompile for every distinct flush size
        if getattr(self, "_multiproc", False):
            # replicated device trees are not fully addressable across
            # processes; pull the local replica of each array
            host_trees = jax.tree.map(_to_host, trees)
        else:
            host_trees = jax.device_get(trees)
        # copy-on-write: mutate a private list and rebind once, so code
        # reading self.models WITHOUT the trees mutex (model text dumps,
        # leaf-value bounds) always sees a self-consistent list — either
        # fully pre-flush or fully post-flush, never mid-append
        models = list(self.models)
        for i, one in enumerate(host_trees):
            ht = HostTree(one, shrinkage=shrinks[i])
            if ht.num_nodes == 0:
                ht.num_leaves = 1
            models.append(ht)
        # stop if the last flushed iteration had no splits at all
        # (reference: gbdt.cpp:440-450 — the failed iteration's trees are
        # popped unless they are the very first, which stay as constant trees)
        stop = False
        tail = models[-k:]
        if len(tail) == k and all(m.num_nodes == 0 for m in tail):
            if len(models) > k:
                models = models[:-k]
                # removal: drop any cached stack holding the popped tail
                self._invalidate_device_trees()
            self.iter_ -= 1
            log.warning("Stopped training because there are no more leaves "
                        "that meet the split requirements")
            stop = True
        self.models = models
        self._dev_trees = []
        return stop

    def _renew_tree_output(self, tree: TreeArrays, row_leaf, mask,
                           cur_tree_id: int) -> TreeArrays:
        """(reference: TreeLearner::RenewTreeOutput + objective RenewTreeOutput,
        regression_objective.hpp:197)"""
        obj = self.objective
        if obj is None or not obj.renew_leaves:
            return tree
        residual = obj.label - self.train_score[cur_tree_id]
        w = mask if self.row_weight is None else mask * self.row_weight
        rung = self.grower_params.num_leaves
        renewed = renew_leaf_quantile(
            residual, w, row_leaf, rung, float(obj.renew_alpha))
        # only leaves that exist keep renewed values (others stay at 0)
        live = jnp.arange(rung) < tree.num_leaves
        return tree._replace(
            leaf_value=jnp.where(live, renewed, tree.leaf_value))

    def _update_score(self, host: HostTree, tree: TreeArrays, row_leaf,
                      cur_tree_id: int) -> None:
        """(reference: GBDT::UpdateScore, gbdt.cpp:491)"""
        self.train_score = self.train_score.at[cur_tree_id].set(
            _add_leaf_outputs(self.train_score[cur_tree_id],
                              tree.leaf_value, row_leaf))
        self._update_valid_scores(tree, cur_tree_id)

    def _update_valid_scores(self, tree: TreeArrays, cur_tree_id: int) -> None:
        for vs in self.valid_sets:
            leaf = route_one_tree(
                vs.binned, tree.split_feature, tree.split_bin,
                tree.cat_bitset, tree.default_left, tree.left_child,
                tree.right_child, tree.num_nodes, *self._route_args())
            vs.score = vs.score.at[cur_tree_id].set(
                _add_leaf_outputs(vs.score[cur_tree_id], tree.leaf_value, leaf))

    def apply_tree_to_scores(self, host: HostTree, cur_tree_id: int,
                             factor: float, train: bool = True,
                             valid: bool = True) -> None:
        """Add ``factor * tree_output`` to cached scores — the workhorse behind
        rollback and DART drop/normalize (reference: Tree::Shrinkage +
        ScoreUpdater::AddScore combos in gbdt.cpp:454 / dart.hpp:131-198)."""
        sf = jnp.asarray(host.split_feature)
        sb = jnp.asarray(host.split_bin)
        cb = jnp.asarray(host.cat_bitset)
        dl = jnp.asarray(host.default_left)
        lc = jnp.asarray(host.left_child)
        rc = jnp.asarray(host.right_child)
        nn = jnp.asarray(host.num_nodes)
        lv = jnp.asarray(host.leaf_value * factor)
        if getattr(host, "is_linear", False):
            # linear leaves contributed leaf_const + x.coeff to the scores;
            # replay the same formula (host-side) for exact add/subtract
            from .linear import linear_leaf_outputs
            if train:
                leaf = route_one_tree(
                    self._routing_binned(), sf, sb, cb, dl, lc, rc, nn,
                    *self._route_args())
                delta = linear_leaf_outputs(
                    host, self.train_set.raw_data, np.asarray(leaf)) * factor
                self.train_score = self.train_score.at[cur_tree_id].add(
                    jnp.asarray(delta, jnp.float32))
            if valid:
                for vs in self.valid_sets:
                    vleaf = route_one_tree(
                        vs.binned, sf, sb, cb, dl, lc, rc, nn,
                        *self._route_args())
                    vdelta = linear_leaf_outputs(
                        host, vs.dataset.raw_data,
                        np.asarray(vleaf)[: vs.n_real]) * factor
                    vs.score = vs.score.at[cur_tree_id, : vs.n_real].add(
                        jnp.asarray(vdelta, jnp.float32))
            return
        if train:
            leaf = route_one_tree(self._routing_binned(), sf, sb, cb, dl,
                                  lc, rc, nn, *self._route_args())
            self.train_score = self.train_score.at[cur_tree_id].set(
                _add_leaf_outputs(self.train_score[cur_tree_id], lv, leaf))
        if valid:
            for vs in self.valid_sets:
                vleaf = route_one_tree(vs.binned, sf, sb, cb, dl, lc, rc,
                                       nn, *self._route_args())
                vs.score = vs.score.at[cur_tree_id].set(
                    _add_leaf_outputs(vs.score[cur_tree_id], lv, vleaf))

    def rollback_one_iter(self) -> None:
        """(reference: GBDT::RollbackOneIter, gbdt.cpp:454)"""
        self._flush_trees()
        if self.iter_ <= 0:
            return
        k = self.num_tree_per_iteration
        for cur_tree_id in range(k):
            host = self.models[len(self.models) - k + cur_tree_id]
            self.apply_tree_to_scores(host, cur_tree_id, -1.0)
        del self.models[len(self.models) - k:]
        self._invalidate_device_trees()
        self.iter_ -= 1

    # -- checkpoint / resume (io/checkpoint.py; reference: the model-text
    # snapshots of gbdt.cpp:250-254 + init_model warm starts — here the
    # snapshot is the COMPLETE optimizer state so resume is bit-identical)
    def snapshot_compatible(self, state) -> Optional[str]:
        """Reason this training run cannot resume from ``state`` (None =
        compatible). Structural checks only — a resumed run is expected to
        use the same params as the interrupted one."""
        if not isinstance(state, dict) or state.get("format") != 1:
            return "unknown snapshot format"
        meta = state.get("meta", {})
        want = {"boosting": self.boosting_type, "num_data": self._n_real,
                "trees_per_iteration": self.num_tree_per_iteration,
                "num_leaves": self.max_leaves}
        for key, val in want.items():
            if meta.get(key) != val:
                return f"{key}: snapshot has {meta.get(key)!r}, " \
                       f"this run has {val!r}"
        names = [n for n, _ in state.get("valid_scores", ())]
        if names != [vs.name for vs in self.valid_sets]:
            return (f"validation sets differ (snapshot {names}, run "
                    f"{[vs.name for vs in self.valid_sets]})")
        expect_compact = bool(self._use_compact
                              and int(state.get("iteration", 0)) >= 1)
        if (state.get("compact") is not None) != expect_compact:
            return ("row-storage layout differs (compact vs masked grower "
                    "— tpu_grower or data size changed)")
        return None

    def capture_training_state(self) -> Dict[str, Any]:
        """Host snapshot of the complete training state.

        The ONLY planned device->host transfers outside stop checks: one
        batched fetch per ``tpu_checkpoint_freq`` boundary, off the jit
        hot path (the steady-state guard asserts exactly this in
        tests/test_checkpoint.py). Covers everything a bit-identical
        resume needs: trees, iteration counter, cached train/valid
        scores, sampling/feature RNG state, bagging cache, CEGB state,
        the compact grower's permuted row records, and (via subclass
        hooks) DART drop state."""
        self._flush_trees()
        with self._trees_mu:
            models = list(self.models)
        strat = self.sample_strategy
        bag_cached = getattr(strat, "_cached", None)
        obj = self.objective
        pos_biases = getattr(obj, "pos_biases", None)
        state: Dict[str, Any] = {
            "format": 1,
            "meta": {
                "boosting": self.boosting_type,
                "num_data": self._n_real,
                "trees_per_iteration": self.num_tree_per_iteration,
                "num_leaves": self.max_leaves,
            },
            "iteration": int(self.iter_),
            "models": models,
            "shrinkage_rate": float(self.shrinkage_rate),
            "init_scores": list(self._init_scores),
            "has_init_score": bool(self._has_init_score),
            "train_score": _to_host(self.train_score),
            "valid_scores": [(vs.name, _to_host(vs.score))
                             for vs in self.valid_sets],
            "feat_rng": self._feat_rng.get_state(),
            "bag_cached": None if bag_cached is None
            else _to_host(bag_cached),
            "cegb_used": None if self._cegb_used is None
            else _to_host(self._cegb_used),
            "cegb_charged": None if self._cegb_charged is None
            else _to_host(self._cegb_charged),
            "pos_biases": None if pos_biases is None
            else _to_host(pos_biases),
            "linear_any_split": bool(getattr(self, "_linear_any_split",
                                             False)),
            "compact": None,
        }
        if self._compact is not None:
            # the permuted row records ARE load-bearing for bit-identity:
            # histogram/score summation order follows the physical row
            # order, so resume must restore the exact bytes, not rebuild
            # from the original order
            c = self._compact
            state["compact"] = {
                "work": _to_host(c["work"]),
                "scratch": _to_host(c["scratch"]),
                "epoch": int(c["epoch"]),
            }
        return state

    def restore_training_state(self, state: Dict[str, Any]) -> None:
        """Rebind this (freshly constructed) trainer to a snapshot. The
        caller validates ``snapshot_compatible`` first."""
        with self._trees_mu:
            self.models = list(state["models"])
            self._dev_trees = []
            self._invalidate_device_trees()
        self.iter_ = int(state["iteration"])
        self.shrinkage_rate = float(state["shrinkage_rate"])
        self._init_scores = list(state["init_scores"])
        self._has_init_score = bool(state["has_init_score"])
        self.train_score = _device_put_like(state["train_score"],
                                            self.train_score)
        for vs, (name, arr) in zip(self.valid_sets, state["valid_scores"]):
            vs.score = _device_put_like(arr, vs.score)
        self._feat_rng.set_state(state["feat_rng"])
        if state.get("bag_cached") is not None \
                and hasattr(self.sample_strategy, "_cached"):
            self.sample_strategy._cached = _device_put_like(
                state["bag_cached"], self.sample_strategy._cached)
        if state.get("cegb_used") is not None:
            self._cegb_used = _device_put_like(state["cegb_used"],
                                               self._cegb_used)
        if state.get("cegb_charged") is not None:
            self._cegb_charged = _device_put_like(state["cegb_charged"],
                                                  self._cegb_charged)
        if state.get("pos_biases") is not None \
                and self.objective is not None:
            self.objective.pos_biases = _device_put_like(
                state["pos_biases"], getattr(self.objective, "pos_biases",
                                             None))
        self._linear_any_split = bool(state.get("linear_any_split", False))
        comp = state.get("compact")
        if comp is not None:
            if self._compact is None:
                self._setup_compact_state()
            c = self._compact
            c["work"] = _device_put_like(comp["work"], c["work"])
            c["scratch"] = _device_put_like(comp["scratch"], c["scratch"])
            c["epoch"] = int(comp["epoch"])
            c["perm_epoch"] = -1
            c["perm"] = None

    def _routing_binned(self) -> jax.Array:
        """Binned rows in the same order as the cached train scores (the
        compact grower permutes rows; DART drops / rollback route through
        the current work order)."""
        if self._compact is not None:
            f = self._compact["layout"].num_features
            return self._compact_rows(self._compact["work"])[:, :f]
        return self.binned

    # -- evaluation ----------------------------------------------------------
    def eval_train(self) -> List[Tuple[str, str, float, bool]]:
        if self._compact is not None and self.train_metrics:
            # train scores live in the compact grower's permuted row order;
            # un-permute them back to the ORIGINAL order so every metric —
            # including query-structured NDCG/MAP — sees its own layout
            # (pad rows carry ids >= n_real and drop out of the slice)
            perm = self._compact_perm()
            raw = _to_host(self.train_score)
            unperm = np.empty_like(raw)
            unperm[:, perm] = raw
            return self._eval("training", unperm[:, :self._n_real],
                              self.train_metrics)
        return self._eval("training", _to_host(self.train_score),
                          self.train_metrics)

    def eval_valid(self) -> List[Tuple[str, str, float, bool]]:
        out = []
        for vs in self.valid_sets:
            out.extend(self._eval(vs.name, _to_host(vs.score), vs.metrics,
                                  n_real=vs.n_real))
        return out

    def _eval(self, name, score, metrics, n_real: Optional[int] = None):
        convert = self.objective.convert_output if self.objective else None
        if n_real is None:
            n_real = self._n_real if hasattr(self, "_n_real") else score.shape[1]
        score = score[:, :n_real]
        raw = score[0] if self.num_tree_per_iteration == 1 else score
        out = []
        for m in metrics:
            if hasattr(m, "eval_all"):
                for k_at, v in zip(m.eval_at, m.eval_all(raw)):
                    out.append((name, f"{m.name}@{k_at}", v, m.higher_better))
            else:
                out.append((name, m.name, m.eval(raw, convert), m.higher_better))
        return out

    # -- prediction ----------------------------------------------------------
    def _predict_cfg(self):
        """(tbatch, row-bucket ladder, engine) resolved from config per
        call — cheap, and reset_parameter may change them mid-session."""
        cfg = self.config
        tb = max(1, min(int(cfg.get("tpu_predict_tbatch", 16) or 16), 128))
        ladder = parse_bucket_ladder(cfg.get("tpu_predict_buckets", "auto"))
        engine = str(cfg.get("tpu_predict_engine", "batched")).lower()
        return tb, ladder, engine

    def _pred_route_args(self):
        """(nan_bin, is_cat) in ORIGINAL feature space — prediction inputs
        are binned per original feature (no bundling)."""
        if self._efb is not None:
            return self._orig_nan_arr, self._orig_cat_arr
        return self.nan_bin_arr, self.is_cat_arr

    def _model_window(self, num_iteration: Optional[int],
                      start_iteration: int) -> List[HostTree]:
        """Model slice for a prediction window (reference: start_iteration
        in GBDT::Predict* / Predictor; num_iteration_for_pred_)."""
        models = self.models
        k = self.num_tree_per_iteration
        if start_iteration > 0:
            models = models[start_iteration * k:]
        if num_iteration is not None and num_iteration > 0:
            models = models[: num_iteration * k]
        return models

    @staticmethod
    def _models_max_depth(models: Sequence[HostTree]) -> int:
        """Deepest root-to-leaf path in the window — the walk-step count
        the engine needs (recorded per HostTree by the grower)."""
        return max((int(np.max(m.leaf_depth[:m.num_leaves], initial=0))
                    for m in models), default=0)

    def _device_trees_plain(self, num_iteration: Optional[int] = None,
                            start_iteration: int = 0):
        """(unpadded StackedTrees, t_real): the pre-engine layout, kept for
        tpu_predict_engine=scan (parity/bench reference path)."""
        with self._trees_mu:
            self._flush_trees()
            models = self._model_window(num_iteration, start_iteration)
            max_lv = max((len(m.leaf_value) for m in models),
                         default=self.max_leaves)
            return stack_trees(models, max_lv - 1, max_lv), len(models)

    #: device-tree cache slots kept before evicting the oldest (each slot
    #: holds one padded model copy on device; serving uses 1-2 slots)
    _DTC_SLOTS = 8

    def _invalidate_device_trees(self) -> None:
        """Drop BOTH device model caches — the padded tree stacks AND
        the TreeSHAP path arrays. Every mutation that invalidates one
        invalidates the other: a rollback/RF/DART leaf rescale changes
        leaf values (and expected values) without necessarily changing
        the tree count, so the paths' cached ``ev`` would silently
        serve stale contributions if it outlived the stack. The cached
        host score baseline (drift_reference) rides along: those same
        mutations change train_score at an unchanged tree count."""
        self._device_trees_cache = None
        self._shap_paths_cache = None
        self._drift_score_host = None
        self._serve_engine_memo = None
        self._shap_tables_cache = None

    def _device_trees_batched(self, num_iteration: Optional[int] = None,
                              start_iteration: int = 0, tbatch: int = 16):
        """(StackedTrees padded to the tree-count bucket, t_real, depth).

        Cached per (tbatch, start_iteration, num_iteration) and
        APPEND-PADDED: trees grown since the last fill are stacked alone
        (a transfer the size of the delta, not the model) and written
        into the padded device arrays, so mid-train predict stops
        re-stacking the whole model every iteration. Windows are
        first-class keys because they ARE the common serving shape —
        Booster.predict defaults num_iteration to best_iteration after
        early-stopped training — and the models list is append-only, so
        a window's contents are stable under appends. Distinct chunk
        sizes (plain vs early-stop predicts) get their own slots; the
        oldest slot is evicted past _DTC_SLOTS. Cache fill and
        model-list read run under the trees mutex so concurrent
        read-locked predicts (basic.py) see a consistent (models, cache)
        pair — the reference serializes the same window behind its
        shared C API lock (src/c_api.cpp:163).
        """
        with self._trees_mu:
            self._flush_trees()
            models = self._model_window(num_iteration, start_iteration)
            t = len(models)
            # width from the models themselves: num_leaves may have been
            # changed mid-training via reset_parameter
            max_lv = max((len(m.leaf_value) for m in models),
                         default=self.max_leaves)
            cat_w = max((m.cat_bitset.shape[1] for m in models), default=1)
            t_bkt = tree_bucket(t, tbatch)
            if self._device_trees_cache is None:
                self._device_trees_cache = {}
            cache = self._device_trees_cache
            key = (tbatch, start_iteration,
                   num_iteration if num_iteration is not None
                   and num_iteration > 0 else None)
            c = cache.get(key)
            if (c is not None and c["max_lv"] == max_lv
                    and c["cat_w"] == cat_w and t >= c["t_real"]):
                if t > c["t_real"]:
                    t0 = c["t_real"]
                    fresh = stack_trees(models[t0:], max_lv - 1, max_lv,
                                        cat_w=cat_w)
                    st = c["st"]
                    if t_bkt != c["t_bucket"]:
                        # bucket grew: extend the padded arrays on device
                        # (the old trees never re-cross PCIe)
                        grow = t_bkt - c["t_bucket"]
                        st = jax.tree.map(
                            lambda a: jnp.concatenate(
                                [a, jnp.zeros((grow,) + a.shape[1:],
                                              a.dtype)]), st)
                    st = jax.tree.map(lambda a, new: a.at[t0:t].set(new),
                                      st, fresh)
                    c.update(st=st, t_real=t, t_bucket=t_bkt,
                             depth=max(c["depth"],
                                       self._models_max_depth(models[t0:])))
                    # derived serving slabs (level heap, quantized
                    # leaves) were built from the pre-append stack —
                    # drop them; the next serving predict rebuilds
                    for derived in ("level", "level_depth", "quant"):
                        c.pop(derived, None)
                return c["st"], c["t_real"], c["depth"]
            depth = self._models_max_depth(models)
            st = stack_trees(models, max_lv - 1, max_lv, cat_w=cat_w,
                             pad_to=t_bkt)
            cache[key] = {
                "st": st, "t_real": t, "t_bucket": t_bkt, "depth": depth,
                "max_lv": max_lv, "cat_w": cat_w}
            while len(cache) > self._DTC_SLOTS:
                cache.pop(next(k for k in cache if k != key))
            return st, t, depth

    def _device_trees_entry(self, num_iteration: Optional[int],
                            start_iteration: int, tbatch: int):
        """(st, t_real, depth, cache-slot dict) — the slot carries the
        derived serving slabs (level heap / quantized leaves) next to
        the padded stack they were built from."""
        st, t_real, depth = self._device_trees_batched(
            num_iteration, start_iteration, tbatch)
        key = (tbatch, start_iteration,
               num_iteration if num_iteration is not None
               and num_iteration > 0 else None)
        with self._trees_mu:
            c = (self._device_trees_cache or {}).get(key)
        return st, t_real, depth, c

    # -- serving engines (ROADMAP 4: level-order relayout + leaf quant) ------
    def _level_cap(self) -> int:
        try:
            cap = int(self.config.get("tpu_level_depth_cap",
                                      DEFAULT_LEVEL_DEPTH_CAP)
                      or DEFAULT_LEVEL_DEPTH_CAP)
        except (TypeError, ValueError):
            cap = DEFAULT_LEVEL_DEPTH_CAP
        return max(1, cap)

    def _level_state(self, c: Dict[str, Any], depth: int):
        """The LevelTrees heap relayout for a device-tree cache slot,
        built once at stack time per (stack, depth) and cached in the
        slot (the _device_trees_cache half of the level engine)."""
        depth = max(1, depth)
        with self._trees_mu:
            lv = c.get("level")
            if lv is not None and c.get("level_depth") == depth:
                return lv
        nan_a, cat_a = self._pred_route_args()
        lv = build_level_layout(c["st"], nan_a, cat_a, depth)
        with self._trees_mu:
            c["level"], c["level_depth"] = lv, depth
        return lv

    def _quant_mode(self) -> Optional[str]:
        """Validated ``tpu_leaf_quant`` (None = off)."""
        m = str(self.config.get("tpu_leaf_quant", "off") or "off").lower()
        if m in ("", "off", "0", "false", "none"):
            return None
        if m not in ("int8", "f16"):
            if not getattr(self, "_warned_leaf_quant", False):
                log.warning(f"tpu_leaf_quant={m!r} is not one of "
                            "off|int8|f16; serving f32 leaves")
                self._warned_leaf_quant = True
            return None
        return m

    def _quant_state(self, c: Dict[str, Any], mode: str):
        """(slab, scale, recorded bound) for a cache slot: the
        quantized serving leaf values with per-tree scales and the
        RECORDED max-score-error bound, computed once at stack time and
        shipped in the slot next to the stack."""
        with self._trees_mu:
            q = c.get("quant")
            if q is not None and q[0] == mode:
                return q[1], q[2], q[3]
        k = max(self.num_tree_per_iteration, 1)
        t_total = c["st"].leaf_value.shape[0]
        class_ids = jnp.arange(t_total, dtype=jnp.int32) % k
        slab, scale, bound = quantize_leaves(
            c["st"].leaf_value, class_ids, mode, num_class=k)
        q = (mode, slab, scale, float(bound))
        with self._trees_mu:
            c["quant"] = q
        return q[1], q[2], q[3]

    def leaf_quant_bound(self, num_iteration: Optional[int] = None,
                         start_iteration: int = 0) -> Optional[float]:
        """The recorded max-score-error bound the quantized model stack
        ships: an exact upper bound on |quantized raw score - f32 raw
        score| for ANY row (per-tree worst-case dequantization error,
        summed per class, maxed over classes). None when
        ``tpu_leaf_quant`` is off."""
        mode = self._quant_mode()
        if mode is None:
            return None
        tb = self._predict_cfg()[0]
        _, t_real, _, c = self._device_trees_entry(
            num_iteration, start_iteration, tb)
        if t_real == 0 or c is None:
            return 0.0
        return self._quant_state(c, mode)[2]

    def _resolve_serving_engine(self, engine: str, depth: int,
                                tbatch: int, t_bkt: int,
                                c: Optional[Dict[str, Any]] = None) -> str:
        """``walk`` or ``level`` via the registry's serving resolve
        order (user > env > autotune cache > depth heuristic), memoized
        per (engine knob, depth, tree bucket, K)."""
        from ..engines import registry as engreg
        cap = self._level_cap()
        k = max(self.num_tree_per_iteration, 1)
        memo = getattr(self, "_serve_engine_memo", None)
        if memo is None:
            memo = self._serve_engine_memo = {}
        key = (engine, depth, t_bkt, k, cap)
        hit = memo.get(key)
        if hit is not None:
            return hit
        racer = None
        if c is not None and engine == "auto":
            racer = lambda: self._serving_race_runners(c, depth, tbatch)
        res = engreg.resolve_serving_engine(
            self.config, depth=depth, level_cap=cap, tree_bucket=t_bkt,
            num_class=k, quant=self._quant_mode() or "off", racer=racer)
        memo[key] = res.engine
        if res.source != "user":
            log.info(f"serving engine: {res.entry_id} "
                     f"({res.source}; depth={depth}, cap={cap})")
        return res.engine

    def _serving_race_runners(self, c: Dict[str, Any], depth: int,
                              tbatch: int):
        """(runners dict, rows) for the autotuner's serving race: walk
        vs level (vs their quantized-slab twins when tpu_leaf_quant is
        on), each a zero-arg dispatch of the REAL stacked trees over a
        small rung — timed by engines/autotune.serving_decision_for."""
        st = c["st"]
        n = 2048
        f = self.train_set.num_total_features
        dev = jnp.zeros((n, f), self.train_set.binned.dtype)
        nan_a, cat_a = self._pred_route_args()
        k = max(self.num_tree_per_iteration, 1)
        kk = np.int32(k)
        qmode = self._quant_mode()
        slab, scale = ((self._quant_state(c, qmode)[:2])
                       if qmode else (st.leaf_value, None))
        walk_st = st._replace(leaf_value=slab) if qmode else st
        runners = {"walk": lambda: predict_raw_batched(
            dev, walk_st, nan_a, cat_a, kk, num_class=k,
            depth=depth_bucket(depth), tbatch=tbatch,
            any_cat=self._pred_any_cat, leaf_scale=scale)}
        if depth <= self._level_cap():
            lvt = self._level_state(c, depth)
            runners["level"] = lambda: predict_raw_level(
                dev, lvt, slab, kk, num_class=k, depth=max(1, depth),
                tbatch=tbatch, any_cat=self._pred_any_cat,
                leaf_scale=scale)
        return runners, n

    def _pad_request_to_bucket(self, mat: np.ndarray, rung: int,
                               packed: bool) -> jax.Array:
        """Host-pad a request matrix to its bucket rung and device_put.

        Pure numpy + one transfer: no compilation, no device->host — the
        zero-recompile serving contract depends on the padding happening
        BEFORE the array reaches a jitted program (tpulint R002)."""
        if mat.shape[0] != rung:
            mat = np.pad(mat, ((0, rung - mat.shape[0]), (0, 0)))
        if packed:
            from ..io.dataset import pack4_matrix
            mat = pack4_matrix(mat)
        return jnp.asarray(mat)

    def predict_raw_device(self, binned,
                           num_iteration: Optional[int] = None,
                           start_iteration: int = 0,
                           early_stop=None,
                           device_packed: bool = False) -> jax.Array:
        """Raw UNAVERAGED score sums, left on device: [K, n_padded] with
        the first ``binned.shape[0]`` columns valid.

        The serving hot path: numpy requests pad on host up to a bucket
        rung, trees come from the bucketed append-pad cache, and the
        jitted engine program is keyed on (row rung, tree bucket, depth
        bucket, num_class) — after one warmup per rung, mixed batch
        sizes run with zero compiles and zero device->host transfers.
        Requests larger than the ladder run as one GSPMD row-sharded
        program over the training mesh when one exists (each shard padded
        to its own rung), else they are the caller's to slice
        (predict_raw_binned does). ``early_stop`` is an optional
        (margin, freq) pair (reference: prediction_early_stop.cpp)."""
        k = self.num_tree_per_iteration
        n = binned.shape[0]
        tb_cfg, ladder, engine = self._predict_cfg()
        margin, freq = early_stop if early_stop else (0.0, 0)
        use_stop = freq > 0 and margin > 0.0
        nan_a, cat_a = self._pred_route_args()
        if engine == "scan":
            # pre-engine reference path: serial tree scan, jitted on the
            # concrete batch shape (recompiles per size by design)
            st, _ = self._device_trees_plain(num_iteration, start_iteration)
            return predict_raw_scan(
                jnp.asarray(binned), st, nan_a, cat_a, np.int32(k), k,
                early_stop_margin=float(margin) if use_stop else 0.0,
                early_stop_freq=int(freq) if use_stop else 0)
        # with early stopping the tree chunk must land on the reference's
        # exact iteration-multiple-of-freq checkpoints
        tbatch = early_stop_tbatch(k, freq, tb_cfg) if use_stop else tb_cfg
        st, t_real, depth, c = self._device_trees_entry(
            num_iteration, start_iteration, tbatch)
        if t_real == 0:
            return jnp.zeros((k, n), jnp.float32)
        kwargs = dict(
            num_class=k, tbatch=tbatch,
            early_stop_margin=float(margin) if use_stop else 0.0,
            early_stop_freq=int(freq) if use_stop else 0,
            any_cat=self._pred_any_cat)
        kk = np.int32(k)
        eng = self._resolve_serving_engine(engine, depth, tbatch,
                                           st.num_trees, c)
        qmode = self._quant_mode()
        slab, scale = ((self._quant_state(c, qmode)[:2])
                       if qmode else (st.leaf_value, None))
        if eng == "level":
            lvt = self._level_state(c, depth)

            def run(dev, packed_flag):
                return predict_raw_level(
                    dev, lvt, slab, kk, depth=max(1, depth),
                    packed=packed_flag, leaf_scale=scale, **kwargs)
        else:
            walk_st = st._replace(leaf_value=slab) if qmode else st

            def run(dev, packed_flag):
                return predict_raw_batched(
                    dev, walk_st, nan_a, cat_a, kk,
                    depth=depth_bucket(depth), packed=packed_flag,
                    leaf_scale=scale, **kwargs)
        if not isinstance(binned, np.ndarray):
            # device-array input (the serving device-featurize path hands
            # an already-rung-padded — possibly nibble-packed — matrix;
            # internal/test callers may pass unpadded, which pads here)
            rung = bucket_rows(n, ladder)
            if rung is not None and rung != n:
                binned = jnp.pad(binned, ((0, rung - n), (0, 0)))
            return run(binned, device_packed)
        packed = self._pred_pack4
        rung = bucket_rows(n, ladder)
        if rung is not None:
            dev = self._pad_request_to_bucket(binned, rung, packed)
            return run(dev, packed)
        if self._can_shard_predict(n, ladder):
            from ..parallel.mesh import (mesh_axis_sizes, predict_shard_pad,
                                         row_sharding_2d)
            num_shards = mesh_axis_sizes(self.mesh)[0]
            n_pad = predict_shard_pad(n, num_shards, ladder)
            mat = np.pad(binned, ((0, n_pad - n), (0, 0)))
            if packed:
                from ..io.dataset import pack4_matrix
                mat = pack4_matrix(mat)
            dev = jax.device_put(mat, row_sharding_2d(self.mesh))
            return run(dev, packed)
        raise ValueError(
            f"request of {n} rows overflows the serving ladder "
            f"(max {ladder[-1]}) and cannot be row-sharded here; slice it "
            "(predict_raw_binned does) or raise tpu_predict_buckets")

    def _can_shard_predict(self, n: int, ladder) -> bool:
        """True when an oversize request can run as ONE GSPMD row-sharded
        program over the training mesh (per-shard share fits the ladder);
        otherwise callers slice through the largest rung."""
        if self.mesh is None or getattr(self, "_multiproc", False):
            return False
        from ..parallel.mesh import mesh_axis_sizes, predict_shard_pad
        num_shards = mesh_axis_sizes(self.mesh)[0]
        return predict_shard_pad(n, num_shards, ladder) is not None

    def _average_divisor(self, num_iteration: Optional[int],
                         start_iteration: int) -> int:
        """RF ``average_output`` divisor: the iteration count actually
        accumulated in the prediction window after start/num slicing
        (reference: num_iteration_for_pred_). The ONE implementation
        behind every averaging prediction path — predict_raw_binned,
        Booster.predict_device and Booster.predict_serving."""
        with self._trees_mu:
            t_real = len(self._model_window(num_iteration,
                                            start_iteration))
        return max(t_real // max(self.num_tree_per_iteration, 1), 1)

    def predict_raw_binned(self, binned,
                           num_iteration: Optional[int] = None,
                           start_iteration: int = 0,
                           early_stop=None) -> np.ndarray:
        """Raw scores [K, N] for already-binned rows. ``early_stop`` is an
        optional (margin, freq) pair (reference:
        src/boosting/prediction_early_stop.cpp)."""
        self._flush_trees()
        if not self.models:
            n = binned.shape[0]
            return np.zeros((self.num_tree_per_iteration, n), np.float32)
        n = binned.shape[0]
        _, ladder, engine = self._predict_cfg()
        oversize = (engine != "scan" and isinstance(binned, np.ndarray)
                    and bucket_rows(n, ladder) is None
                    and not self._can_shard_predict(n, ladder))
        if oversize:
            # above the ladder with no mesh: slices of the largest rung,
            # each hitting the warm max-rung program (early stopping is
            # per row, so slicing preserves its semantics exactly)
            top = ladder[-1]
            parts = []
            for a in range(0, n, top):
                raw = self.predict_raw_device(
                    binned[a:a + top], num_iteration, start_iteration,
                    early_stop)
                parts.append(np.asarray(raw)[:, :min(top, n - a)])
            raw = np.concatenate(parts, axis=1)
        else:
            raw = np.asarray(self.predict_raw_device(
                binned, num_iteration, start_iteration, early_stop))[:, :n]
        if self.average_output:
            raw = raw / self._average_divisor(num_iteration,
                                              start_iteration)
        return raw

    def bin_matrix(self, arr: np.ndarray) -> np.ndarray:
        """Bin raw feature rows with the training BinMappers (host side)."""
        from ..io.binning import bin_columns
        ds = self.train_set
        arr = np.asarray(arr)
        if arr.dtype != np.float32:     # float32 upcasts exactly per-compare
            arr = arr.astype(np.float64, copy=False)
        if arr.ndim == 1:
            arr = arr.reshape(1, -1)
        if arr.shape[1] != ds.num_total_features:
            raise ValueError(
                f"input has {arr.shape[1]} features, model expects "
                f"{ds.num_total_features}")
        return bin_columns(ds.mappers, arr, ds.binned.dtype)

    # -- serving featurization (ISSUE 13: the one-copy hot path) -------------
    def _serve_featurize_mode(self) -> str:
        """Resolved ``tpu_serve_featurize`` for this model: ``device``
        (default — a serving request is one host->device copy of raw
        float32, binned by the jitted ops/device_bin.py program) or
        ``host`` (the bin_columns parity/escape hatch). Demotes to host
        with a one-time warning when the model cannot take the device
        featurizer (scan engine, int32-overflowing categorical codes)."""
        mode = str(self.config.get("tpu_serve_featurize", "device")).lower()
        if mode not in ("device", "host"):
            log.warning(f"unrecognized tpu_serve_featurize={mode!r}; "
                        "using 'device'")
            mode = "device"
        if mode == "host":
            return "host"
        if self._predict_cfg()[2] == "scan":
            return "host"        # scan path has no rung padding to key on
        return "device" if self._featurize_state() is not None else "host"

    def _featurize_state(self):
        """Device-resident binning state (built once per model), or None
        when the model is not device-featurizable (warned once)."""
        cached = getattr(self, "_featurize_dev", None)
        if cached is not None:
            return cached if cached != "ineligible" else None
        from ..io.binning import export_featurize_state
        from ..ops.device_bin import device_bin_state
        host_state = export_featurize_state(self.train_set.mappers)
        if host_state.reason is not None:
            log.warning(f"tpu_serve_featurize=device unavailable "
                        f"({host_state.reason}); serving bins on host")
            self._featurize_dev = "ineligible"
            return None
        self._featurize_dev = device_bin_state(host_state)
        return self._featurize_dev

    def drift_reference(self):
        """Serving drift-monitor reference (ISSUE 14): ``(bin-occupancy
        probs [F, B], per-feature bin counts [F], training raw margins
        [K, N] device array or None)``.

        The occupancy is the training data's normalized per-feature bin
        distribution (cached on the dataset — the serving registry
        materializes it during the deploy warm phase so it ships WITH
        the model); the margins seed the fixed-edge score-distribution
        baseline and are returned as a CACHED host copy, so the [K, N]
        d2h also happens once, in the warm phase, not at the post-swap
        monitor attach. Live serving windows are compared against both
        (PSI / KL) by obs/drift.DriftMonitor."""
        probs, nbins = self.train_set.reference_bin_distribution()
        self._flush_trees()
        key = len(self.models)          # continued training MUST refresh
        cached = getattr(self, "_drift_score_host", None)
        if cached is None or cached[0] != key:
            ts = getattr(self, "train_score", None)
            cached = (key, False if ts is None else np.asarray(ts))
            self._drift_score_host = cached
        return probs, nbins, (None if cached[1] is False else cached[1])

    def featurize_rung(self, arr32: np.ndarray) -> jax.Array:
        """Pad a raw float32 request to its bucket rung, upload it (THE
        one host->device copy of a serving request) and bin it with the
        jitted featurizer — device-resident bins in the exact layout the
        host path would produce (pack4 included), ready for
        predict_raw_device(device_packed=self._pred_pack4)."""
        from ..ops.device_bin import bin_rows_device
        ds = self.train_set
        if arr32.shape[1] != ds.num_total_features:
            raise ValueError(
                f"input has {arr32.shape[1]} features, model expects "
                f"{ds.num_total_features}")
        n = arr32.shape[0]
        rung = self._serving_rung(n)
        if n != rung:
            arr32 = np.pad(arr32, ((0, rung - n), (0, 0)))
        state = self._featurize_state()
        if state is None:
            raise ValueError("model is not device-featurizable; use the "
                             "host binner (tpu_serve_featurize=host)")
        return bin_rows_device(jnp.asarray(arr32), state, np.int32(n),
                               out_dtype=ds.binned.dtype.name,
                               packed=self._pred_pack4)

    # -- device TreeSHAP / leaf-index serving (ISSUE 13 endpoints) -----------
    #: shap-path cache slots (per prediction window; serving uses 1-2)
    _SHAP_SLOTS = 4

    def _device_shap_state(self, num_iteration: Optional[int],
                           start_iteration: int, tbatch: int):
        """(StackedTrees, ShapPaths, t_real, depth) for a window.

        The tree stack comes from the shared append-pad device cache
        (_device_trees_batched); the per-leaf path arrays are extracted
        once per (window, model length) and cached — the row-independent
        half of TreeSHAP, the analogue of the reference computing each
        tree's decision paths once per PredictContrib call."""
        from ..ops.treeshap_device import build_shap_paths
        st, t_real, depth = self._device_trees_batched(
            num_iteration, start_iteration, tbatch)
        with self._trees_mu:
            # slice to the stacked length: a tree appended between the two
            # mutex sections must not desync paths from the stack
            models = self._model_window(num_iteration,
                                        start_iteration)[:t_real]
            key = (tbatch, start_iteration,
                   num_iteration if num_iteration is not None
                   and num_iteration > 0 else None)
            cache = getattr(self, "_shap_paths_cache", None)
            if cache is None:
                cache = self._shap_paths_cache = {}
            c = cache.get(key)
            d_bkt = depth_bucket(depth)
            if c is not None and c["t_real"] == t_real \
                    and c["d_bkt"] == d_bkt:
                return st, c["paths"], t_real, depth
            paths = build_shap_paths(models, st.leaf_value.shape[1], d_bkt,
                                     pad_to=st.num_trees)
            cache[key] = {"paths": paths, "t_real": t_real, "d_bkt": d_bkt}
            while len(cache) > self._SHAP_SLOTS:
                cache.pop(next(k for k in cache if k != key))
            return st, paths, t_real, depth

    def _shap_table_mode(self) -> str:
        raw = str(self.config.get("tpu_shap_tables", "auto")).strip().lower()
        if raw in ("auto", "on", "off"):
            return raw
        if not getattr(self, "_warned_shap_tables", False):
            self._warned_shap_tables = True
            log.warning(f"tpu_shap_tables={raw!r} unknown (auto|on|off); "
                        "using auto")
        return "auto"

    def _device_shap_tables_bucketed(self, st, paths, t_real: int,
                                     depth: int,
                                     num_iteration: Optional[int],
                                     start_iteration: int, tbatch: int):
        """ShapTables at the window's (tree bucket, depth bucket), or
        None when gated off / over the ``tpu_shap_table_mb`` budget (the
        loop kernel then serves).

        Built once per (window, model length) at deploy time — never on
        the serving path — and cached next to the path arrays (bounded
        by the same ``_SHAP_SLOTS``; the negative decision is cached too
        so the budget check costs one host sync total). Same
        invalidation as every device-tree cache
        (``_invalidate_device_trees``). The build (one host sync for
        mask_bits + the jitted table construction) runs OUTSIDE
        ``_trees_mu`` — concurrent first builders race benignly (same
        inputs, last writer wins), and an invalidation mid-build drops
        the store instead of resurrecting a stale cache."""
        from ..ops.treeshap_device import build_shap_tables, shap_table_bytes
        mode = self._shap_table_mode()
        if mode == "off" or t_real == 0:
            return None
        d_bkt = depth_bucket(depth)
        key = (tbatch, start_iteration,
               num_iteration if num_iteration is not None
               and num_iteration > 0 else None)
        with self._trees_mu:
            cache = getattr(self, "_shap_tables_cache", None)
            if cache is None:
                cache = self._shap_tables_cache = {}
                _register_shap_table_probe(self)
            c = cache.get(key)
            if c is not None and c["t_real"] == t_real \
                    and c["d_bkt"] == d_bkt and c["mode"] == mode:
                return c["tables"]
        mask_bits = int(jax.device_get(jnp.max(paths.ulen)))
        budget_mb = max(int(self.config.get("tpu_shap_table_mb", 64)), 0)
        need = shap_table_bytes(st.num_trees, st.leaf_value.shape[1],
                                mask_bits, d_bkt)
        if need > budget_mb << 20:
            if mode == "on":
                raise ValueError(
                    f"tpu_shap_tables=on but the UNWIND tables need "
                    f"{need / 2**20:.1f} MiB "
                    f"(> tpu_shap_table_mb={budget_mb}); raise the "
                    "budget or use tpu_shap_tables=auto")
            log.info(f"shap tables skipped: {need / 2**20:.1f} MiB over "
                     f"the {budget_mb} MiB budget (loop kernel serves "
                     "pred_contrib)")
            tables = None
        else:
            tables = build_shap_tables(paths, st.leaf_value,
                                       mask_bits=mask_bits, depth=d_bkt)
        with self._trees_mu:
            cache = getattr(self, "_shap_tables_cache", None)
            if cache is not None:
                cache[key] = {"tables": tables, "t_real": t_real,
                              "d_bkt": d_bkt, "mode": mode}
                while len(cache) > self._SHAP_SLOTS:
                    cache.pop(next(k for k in cache if k != key))
        return tables

    def _serving_rung(self, n: int) -> int:
        """Bucket rung for one serving batch, or a structural error when
        the request overflows the ladder — THE one bounds check shared
        by the featurize and host-binned serving paths."""
        _, ladder, _ = self._predict_cfg()
        rung = bucket_rows(n, ladder)
        if rung is None:
            raise ValueError(
                f"request of {n} rows overflows the serving ladder "
                f"(max {ladder[-1]}); slice it or raise "
                "tpu_predict_buckets")
        return rung

    def _serving_device_request(self, binned, device_packed: bool):
        """(device matrix at a rung, packed?) for a serving batch that may
        arrive host-binned (numpy) or device-featurized (jax.Array)."""
        if not isinstance(binned, np.ndarray):
            return binned, device_packed
        rung = self._serving_rung(binned.shape[0])
        return (self._pad_request_to_bucket(binned, rung, self._pred_pack4),
                self._pred_pack4)

    def predict_contrib_padded(self, binned,
                               num_iteration: Optional[int] = None,
                               start_iteration: int = 0,
                               device_packed: bool = False) -> np.ndarray:
        """Exact TreeSHAP contributions [rung, K*(F+1)] via the device
        engine (ops/treeshap_device.py), rung-padded like
        predict_serving — the ``pred_contrib`` serving endpoint's one
        device dispatch. Matches ops/treeshap.py's numpy reference
        within f32 tolerance and sums to the raw score per row."""
        from ..ops.treeshap_device import shap_batched, shap_batched_tables
        k = self.num_tree_per_iteration
        tb_cfg, _, _ = self._predict_cfg()
        f = self.train_set.num_total_features
        st, paths, t_real, depth = self._device_shap_state(
            num_iteration, start_iteration, tb_cfg)
        if t_real == 0:
            return np.zeros((binned.shape[0], k * (f + 1)), np.float32)
        tables = self._device_shap_tables_bucketed(
            st, paths, t_real, depth, num_iteration, start_iteration,
            tb_cfg)
        dev, packed = self._serving_device_request(binned, device_packed)
        nan_a, cat_a = self._pred_route_args()
        if tables is not None:
            out = shap_batched_tables(
                dev, st, tables, nan_a, cat_a, np.int32(k), num_class=k,
                depth=depth_bucket(depth), tbatch=tb_cfg,
                any_cat=self._pred_any_cat, packed=packed, num_features=f)
        else:
            out = shap_batched(dev, st, paths, nan_a, cat_a, np.int32(k),
                               num_class=k, depth=depth_bucket(depth),
                               tbatch=tb_cfg, any_cat=self._pred_any_cat,
                               packed=packed, num_features=f)
        arr = np.asarray(out)                     # [K, rung, F+1]
        return arr.transpose(1, 0, 2).reshape(arr.shape[1], -1)

    def predict_leaf_padded(self, binned,
                            num_iteration: Optional[int] = None,
                            start_iteration: int = 0,
                            device_packed: bool = False) -> np.ndarray:
        """Per-tree leaf indices [rung, t_real] via the depth walk —
        the ``pred_leaf`` serving endpoint (reference: PredictLeafIndex).
        The walk already computes the final node ids for every predict;
        this returns them rung-padded so per-request slicing stays on
        the host (the coalescer's zero-recompile contract)."""
        tb, _, engine = self._predict_cfg()
        st, t_real, depth, c = self._device_trees_entry(
            num_iteration, start_iteration, tb)
        if t_real == 0:
            return np.zeros((binned.shape[0], 0), np.int32)
        dev, packed = self._serving_device_request(binned, device_packed)
        nan_a, cat_a = self._pred_route_args()
        eng = self._resolve_serving_engine(engine, depth, tb,
                                           st.num_trees, c)
        if eng == "level":
            lv = predict_leaf_level(
                dev, self._level_state(c, depth), depth=max(1, depth),
                tbatch=tb, any_cat=self._pred_any_cat, packed=packed)
        else:
            lv = predict_leaf_batched(
                dev, st, nan_a, cat_a, depth=depth_bucket(depth),
                tbatch=tb, any_cat=self._pred_any_cat, packed=packed)
        return np.asarray(lv)[:t_real].T          # [rung, t_real]

    def predict_raw_matrix(self, arr: np.ndarray,
                           num_iteration: Optional[int] = None,
                           start_iteration: int = 0,
                           early_stop=None) -> np.ndarray:
        if getattr(self, "_linear", False):
            from .linear import linear_leaf_outputs
            if early_stop is not None:
                log.warning(
                    "pred_early_stop is ignored with linear_tree models")
            self._flush_trees()
            if arr.ndim == 1:
                arr = arr.reshape(1, -1)
            leaves = self.predict_leaf_matrix(arr, num_iteration,
                                              start_iteration)
            models = self.models[start_iteration
                                 * self.num_tree_per_iteration:]
            if num_iteration is not None and num_iteration > 0:
                models = models[: num_iteration
                                * self.num_tree_per_iteration]
            k = self.num_tree_per_iteration
            out = np.zeros((k, arr.shape[0]), np.float64)
            for i, m in enumerate(models):
                out[i % k] += linear_leaf_outputs(m, arr, leaves[:, i])
            return out.astype(np.float32)
        return self.predict_raw_binned(self.bin_matrix(arr), num_iteration,
                                       start_iteration, early_stop)

    def predict_leaf_matrix(self, arr: np.ndarray,
                            num_iteration: Optional[int] = None,
                            start_iteration: int = 0) -> np.ndarray:
        """Per-row, per-tree leaf indices [N, T] via the walk engine
        (reference: PredictLeafIndex), bucketed like predict_raw_device."""
        binned = self.bin_matrix(arr)
        n = binned.shape[0]
        nan_a, cat_a = self._pred_route_args()
        tb, ladder, engine = self._predict_cfg()
        if engine == "scan":
            from ..ops.predict import predict_leaf_index
            trees, _ = self._device_trees_plain(num_iteration,
                                                start_iteration)
            return np.asarray(predict_leaf_index(
                jnp.asarray(binned), trees, nan_a, cat_a)).T
        st, t_real, depth, c = self._device_trees_entry(
            num_iteration, start_iteration, tb)
        if t_real == 0 or n == 0:
            return np.zeros((n, t_real), np.int32)
        packed = self._pred_pack4
        eng = self._resolve_serving_engine(engine, depth, tb,
                                           st.num_trees, c)
        top = ladder[-1]
        parts = []
        for a in range(0, n, top):
            sl = binned[a:a + top]
            rung = bucket_rows(sl.shape[0], ladder)
            dev = self._pad_request_to_bucket(sl, rung, packed)
            if eng == "level":
                lv = predict_leaf_level(
                    dev, self._level_state(c, depth),
                    depth=max(1, depth), tbatch=tb,
                    any_cat=self._pred_any_cat, packed=packed)
            else:
                lv = predict_leaf_batched(
                    dev, st, nan_a, cat_a, depth=depth_bucket(depth),
                    tbatch=tb, any_cat=self._pred_any_cat, packed=packed)
            parts.append(np.asarray(lv)[:t_real, :sl.shape[0]])
        return np.concatenate(parts, axis=1).T

    @property
    def current_iteration(self) -> int:
        return self.num_total_trees // max(self.num_tree_per_iteration, 1)

    # -- feature importance (reference: GBDT::FeatureImportance, gbdt.cpp) ---
    def feature_importance(self, importance_type: str = "split",
                           iteration: Optional[int] = None) -> np.ndarray:
        num_features = getattr(self, "_num_orig_features", None) \
            or int(self.binned.shape[1]) if hasattr(self, "binned") \
            else max((int(m.split_feature.max(initial=-1)) + 1)
                     for m in self.models) if self.models else 0
        out = np.zeros(num_features, np.float64)
        self._flush_trees()
        models = self.models
        if iteration is not None and iteration > 0:
            models = models[: iteration * self.num_tree_per_iteration]
        for m in models:
            for i in range(m.num_nodes):
                f = int(m.split_feature[i])
                if f < 0:
                    continue
                if importance_type == "split":
                    out[f] += 1.0
                else:
                    out[f] += max(float(m.split_gain[i]), 0.0)
        return out
