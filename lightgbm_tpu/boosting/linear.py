"""Linear leaves (``linear_tree=true``).

TPU-adapted re-design of the reference's LinearTreeLearner
(reference: src/treelearner/linear_tree_learner.cpp — per-leaf weighted
least squares ``beta = -(X^T H X + lambda I)^{-1} X^T g`` over the numerical
features on the leaf's path, NaN rows skipped, near-zero coefficients
dropped, NaN prediction falls back to the constant leaf value,
include/LightGBM/tree.h:587 Predict).

The reference restricts linear trees to its CPU learner (no CUDA support);
here the tree STRUCTURE still grows on-device, and the per-leaf solves run
host-side in numpy — leaves are few and the solves are tiny, so this is a
host-orchestrated mode like the reference's, not a device kernel.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

_ZERO = 1e-35


def path_features(host, leaf: int, is_cat: np.ndarray) -> List[int]:
    """Numerical features on the path from the root to ``leaf``
    (reference: Tree branch_features with categorical features excluded,
    linear_tree_learner.cpp GetLeafMap/InitLinear)."""
    feats = []
    node = int(host.leaf_parent[leaf])
    while node >= 0:
        f = int(host.split_feature[node])
        if f >= 0 and not bool(is_cat[f]) and f not in feats:
            feats.append(f)
        # walk up: find the parent node pointing at `node`
        parents = np.where((host.left_child == node)
                           | (host.right_child == node))[0]
        node = int(parents[0]) if len(parents) else -1
    return sorted(feats)


def fit_linear_leaves(host, raw: np.ndarray, row_leaf: np.ndarray,
                      grad: np.ndarray, hess: np.ndarray,
                      is_cat: np.ndarray, linear_lambda: float,
                      shrinkage: float = 1.0) -> None:
    """Fit each leaf's linear model in place on the HostTree (adds
    leaf_const / leaf_features / leaf_coeff). ``host.leaf_value`` arrives
    already scaled by the learning rate, so fitted betas scale here and
    constant-fallback leaves keep the already-scaled value untouched."""
    nl = host.num_leaves
    host.leaf_const = np.array(host.leaf_value[:len(host.leaf_value)],
                               np.float64).copy()
    host.leaf_features = [[] for _ in range(len(host.leaf_value))]
    host.leaf_coeff = [[] for _ in range(len(host.leaf_value))]
    host.is_linear = True
    for leaf in range(nl):
        feats = path_features(host, leaf, is_cat)
        if not feats:
            host.leaf_const[leaf] = float(host.leaf_value[leaf])
            continue
        rows = np.flatnonzero(row_leaf == leaf)
        if rows.size == 0:
            host.leaf_const[leaf] = float(host.leaf_value[leaf])
            continue
        x = raw[np.ix_(rows, feats)]
        ok = ~np.isnan(x).any(axis=1)
        rows = rows[ok]
        x = x[ok]
        # too little data for a stable solve: keep the constant model
        # (reference: num < num_feat * 2 check in CalculateLinear)
        if rows.size < 2 * (len(feats) + 1):
            host.leaf_const[leaf] = float(host.leaf_value[leaf])
            continue
        g = grad[rows].astype(np.float64)
        h = hess[rows].astype(np.float64)
        xi = np.column_stack([x, np.ones(len(x))])
        xthx = xi.T @ (xi * h[:, None])
        # ridge on the feature diagonal only (not the intercept)
        xthx[np.arange(len(feats)), np.arange(len(feats))] += linear_lambda
        xtg = xi.T @ g
        try:
            beta = -np.linalg.solve(xthx, xtg)
        except np.linalg.LinAlgError:
            host.leaf_const[leaf] = float(host.leaf_value[leaf])
            continue
        if not np.isfinite(beta).all():
            host.leaf_const[leaf] = float(host.leaf_value[leaf])
            continue
        beta = beta * shrinkage
        keep = np.abs(beta[:-1]) > _ZERO
        host.leaf_features[leaf] = [f for f, k in zip(feats, keep) if k]
        host.leaf_coeff[leaf] = [float(b) for b, k in zip(beta[:-1], keep)
                                 if k]
        host.leaf_const[leaf] = float(beta[-1])


def linear_leaf_outputs(host, raw: np.ndarray, leaf: np.ndarray) -> np.ndarray:
    """Per-row outputs of a linear tree (NaN in a needed feature falls back
    to the constant leaf value — reference: tree.h:587)."""
    out = np.asarray(host.leaf_value, np.float64)[leaf].copy()
    for l in range(host.num_leaves):
        feats = host.leaf_features[l]
        rows = np.flatnonzero(leaf == l)
        if rows.size == 0:
            continue
        if not feats:
            out[rows] = host.leaf_const[l]
            continue
        x = raw[np.ix_(rows, feats)]
        ok = ~np.isnan(x).any(axis=1)
        vals = host.leaf_const[l] + x[ok] @ np.asarray(host.leaf_coeff[l])
        out[rows[ok]] = vals
    return out


def add_bias_linear(host, bias: float) -> None:
    host.leaf_const = np.asarray(host.leaf_const) + bias
