"""Boosting layer: GBDT / DART / RF drivers.

Factory mirrors the reference's Boosting::CreateBoosting
(reference: src/boosting/boosting.cpp:34).
"""
from __future__ import annotations

from .dart import DART
from .gbdt import GBDT, HostTree, stack_trees
from .rf import RF


def create_boosting(config, train_set=None, objective=None) -> GBDT:
    boosting = str(config.get("boosting", "gbdt")).lower()
    if boosting in ("gbdt", "gbrt", "goss"):
        return GBDT(config, train_set, objective)
    if boosting == "dart":
        return DART(config, train_set, objective)
    if boosting in ("rf", "random_forest"):
        return RF(config, train_set, objective)
    raise ValueError(f"Unknown boosting type: {boosting}")


__all__ = ["GBDT", "DART", "RF", "HostTree", "create_boosting", "stack_trees"]
