"""DART boosting: dropout of trees per iteration.

Mirror of the reference's DART (reference: src/boosting/dart.hpp:23 — the
drop → train → normalize cycle: DroppingTrees :97, Normalize :158, shrinkage
bookkeeping in TrainOneIter :58).

The reference expresses drop/normalize as Shrinkage(-1)/Shrinkage(1/(k+1))/
Shrinkage(-k) + AddScore sequences; here the algebra is collapsed: a dropped
tree is subtracted from the train score before gradient computation, and after
the new tree lands every dropped tree is rescaled to ``k/(k+1)`` of itself
(``k+lr`` denominator in xgboost_dart_mode) in the model and in all cached
scores — identical end state, two tree-routing passes per dropped tree.
"""
from __future__ import annotations

from typing import List

import numpy as np

from ..utils import log
from .gbdt import GBDT


class DART(GBDT):
    boosting_type = "dart"

    def __init__(self, config, train_set=None, objective=None):
        super().__init__(config, train_set, objective)
        self.drop_rate = float(config.get("drop_rate", 0.1))
        self.max_drop = int(config.get("max_drop", 50))
        self.skip_drop = float(config.get("skip_drop", 0.5))
        self.uniform_drop = bool(config.get("uniform_drop", False))
        self.xgboost_dart_mode = bool(config.get("xgboost_dart_mode", False))
        self._rng = np.random.RandomState(int(config.get("drop_seed", 4)))
        self.tree_weight: List[float] = []
        self.sum_weight = 0.0

    def capture_training_state(self):
        """DART drop state rides the snapshot: the drop RNG stream and the
        per-tree weights drive which trees future iterations drop, so a
        bit-identical resume must restore them exactly (reference: the
        same fields DART carries across TrainOneIter calls, dart.hpp:97)."""
        state = super().capture_training_state()
        state["dart"] = {
            "rng": self._rng.get_state(),
            "tree_weight": list(self.tree_weight),
            "sum_weight": float(self.sum_weight),
        }
        return state

    def restore_training_state(self, state):
        super().restore_training_state(state)
        dart = state.get("dart")
        if dart is not None:
            self._rng.set_state(dart["rng"])
            self.tree_weight = list(dart["tree_weight"])
            self.sum_weight = float(dart["sum_weight"])

    def _select_drop(self) -> List[int]:
        """(reference: DART::DroppingTrees, dart.hpp:97)"""
        drop: List[int] = []
        if self._rng.rand() < self.skip_drop:
            return drop
        drop_rate = self.drop_rate
        if not self.uniform_drop:
            if self.sum_weight <= 0:
                return drop
            inv_avg = len(self.tree_weight) / self.sum_weight
            if self.max_drop > 0:
                drop_rate = min(drop_rate, self.max_drop * inv_avg / self.sum_weight)
            for i in range(self.iter_):
                if self._rng.rand() < drop_rate * self.tree_weight[i] * inv_avg:
                    drop.append(i)
                    if len(drop) >= self.max_drop:
                        break
        else:
            if self.max_drop > 0 and self.iter_ > 0:
                drop_rate = min(drop_rate, self.max_drop / float(self.iter_))
            for i in range(self.iter_):
                if self._rng.rand() < drop_rate:
                    drop.append(i)
                    if len(drop) >= self.max_drop:
                        break
        return drop

    def train_one_iter(self, gradients=None, hessians=None) -> bool:
        # dropping needs host trees every iteration; a deferred no-split stop
        # detected here must also end training
        if self._flush_trees():
            return True
        k_trees = self.num_tree_per_iteration
        drop_index = self._select_drop()
        k = float(len(drop_index))

        # drop: remove the trees' contribution from the training score so the
        # gradients see the reduced ensemble (reference: dart.hpp:131-137)
        for i in drop_index:
            for tid in range(k_trees):
                host = self.models[i * k_trees + tid]
                self.apply_tree_to_scores(host, tid, -1.0, valid=False)

        # per-iteration shrinkage (reference: dart.hpp:138-147)
        if not self.xgboost_dart_mode:
            self.shrinkage_rate = self.learning_rate / (1.0 + k)
        else:
            self.shrinkage_rate = (
                self.learning_rate if not drop_index
                else self.learning_rate / (self.learning_rate + k))

        ret = super().train_one_iter(gradients, hessians)
        if ret:
            # training stopped: restore dropped trees' score contribution
            for i in drop_index:
                for tid in range(k_trees):
                    host = self.models[i * k_trees + tid]
                    self.apply_tree_to_scores(host, tid, 1.0, valid=False)
            return ret

        # normalize (reference: DART::Normalize, dart.hpp:158): dropped trees
        # end at factor*old where factor = k/(k+1) (or k/(k+lr) in xgb mode)
        denom = (k + 1.0) if not self.xgboost_dart_mode \
            else (k + self.learning_rate)
        factor = k / denom
        for i in drop_index:
            for tid in range(k_trees):
                host = self.models[i * k_trees + tid]
                # valid scores still hold the full old tree: add (factor-1)*old
                self.apply_tree_to_scores(host, tid, factor - 1.0, train=False)
                # train score had it fully removed: add factor*old back
                self.apply_tree_to_scores(host, tid, factor, valid=False)
                host.scale(factor)
            if not self.uniform_drop:
                self.sum_weight -= self.tree_weight[i] * (1.0 / denom)
                self.tree_weight[i] *= factor
        self._invalidate_device_trees()

        if not self.uniform_drop:
            self.tree_weight.append(self.shrinkage_rate)
            self.sum_weight += self.shrinkage_rate
        return False
