"""Random Forest mode: bagged trees without shrinkage, averaged outputs.

Mirror of the reference's RF (reference: src/boosting/rf.hpp — gradients
computed ONCE from the constant init score (Boosting() :110), per-iteration
bagging, no shrinkage, running-average score maintenance in TrainOneIter
(MultiplyScore bracketing :155-160), ``average_output_ = true``).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..ops.grower import grow_tree
from ..utils import log
from .gbdt import GBDT, HostTree


class RF(GBDT):
    _supports_lazy_cegb = False

    boosting_type = "rf"
    average_output = True

    def __init__(self, config, train_set=None, objective=None):
        if config.get("bagging_freq", 0) <= 0 or \
                not (0.0 < config.get("bagging_fraction", 1.0) < 1.0):
            if not (0.0 < config.get("feature_fraction", 1.0) < 1.0):
                raise ValueError(
                    "Random forest needs bagging (bagging_freq > 0 and "
                    "0 < bagging_fraction < 1) and/or feature_fraction < 1")
        super().__init__(config, train_set, objective)
        self.shrinkage_rate = 1.0
        self._const_grad = None

    def _rf_gradients(self):
        """Gradients w.r.t. the constant init score (reference: RF::Boosting)."""
        if self._const_grad is None:
            if self.objective is None:
                raise ValueError("RF mode does not support custom objectives")
            for kk in range(self.num_tree_per_iteration):
                self._init_scores[kk] = self.objective.boost_from_score(kk) \
                    if bool(self.config.get("boost_from_average", True)) else 0.0
            init = jnp.asarray(
                np.asarray(self._init_scores, np.float32))[:, None]
            const_score = jnp.zeros_like(self.train_score) + init
            if self.num_tree_per_iteration == 1:
                g, h = self.objective.get_gradients(const_score[0])
                self._const_grad = (g[None, :], h[None, :])
            else:
                self._const_grad = self.objective.get_gradients(const_score)
        return self._const_grad

    def train_one_iter(self, gradients=None, hessians=None) -> bool:
        if gradients is not None or hessians is not None:
            raise ValueError("RF mode does not support custom objectives")
        k, n = self.num_tree_per_iteration, self.num_data
        grad, hess = self._rf_gradients()
        mask = self.sample_strategy.bag_mask(self.iter_, grad, hess)
        grad, hess = self.sample_strategy.scale_grad_hess(mask, grad, hess)
        if mask is None:
            mask = jnp.ones((n,), jnp.float32)
        feat_mask = self._feature_mask()
        n_prev = float(self.iter_)
        leaf_budget, depth_budget = self._step_budget_args()

        for cur_tree_id in range(k):
            g = grad[cur_tree_id] * mask
            h = hess[cur_tree_id] * mask
            import jax as _jax
            tree, row_leaf = grow_tree(
                self.binned, g, h, mask,
                self.num_bins_arr, self.nan_bin_arr, self.has_nan_arr,
                self.is_cat_arr, feat_mask, self.grower_params,
                self._mono_types, self._inter_sets,
                _jax.random.fold_in(self._bynode_key, self.num_total_trees),
                self._cegb_coupled, self._cegb_state(),
                _jax.random.fold_in(self._extra_key, self.num_total_trees),
                self._feature_contri, self._forced_splits,
                leaf_budget=leaf_budget, depth_budget=depth_budget,
            )
            if self._use_cegb:
                from .gbdt import _tree_used_features
                self._cegb_used = _tree_used_features(
                    tree, int(self.binned.shape[1]), self._cegb_used)
            if int(tree.num_nodes) > 0:
                tree = self._renew_tree_output(tree, row_leaf, mask, cur_tree_id)
                # RF folds the init score into every tree (rf.hpp AddBias)
                init = self._init_scores[cur_tree_id]
                if abs(init) > 1e-10:
                    tree = tree._replace(leaf_value=tree.leaf_value + init)
                host = HostTree(tree, shrinkage=1.0)
                # running average: score = (score*n_prev + tree) / (n_prev+1)
                # (reference: MultiplyScore bracketing, rf.hpp:155-160)
                self.train_score = self.train_score.at[cur_tree_id].multiply(n_prev)
                for vs in self.valid_sets:
                    vs.score = vs.score.at[cur_tree_id].multiply(n_prev)
                self._update_score(host, tree, row_leaf, cur_tree_id)
                self.train_score = self.train_score.at[cur_tree_id].multiply(
                    1.0 / (n_prev + 1.0))
                for vs in self.valid_sets:
                    vs.score = vs.score.at[cur_tree_id].multiply(
                        1.0 / (n_prev + 1.0))
            else:
                host = HostTree(tree, shrinkage=1.0)
                host.num_leaves = 1
                host.num_nodes = 0
                const = self._init_scores[cur_tree_id] \
                    if len(self.models) < k else 0.0
                host.leaf_value = np.full_like(host.leaf_value, const)
                # constant trees get the same running-average bracketing as
                # split trees (reference: rf.hpp MultiplyScore around
                # UpdateScore applies to every iteration) — otherwise cached
                # scores average over the wrong denominator afterwards
                self.train_score = self.train_score.at[cur_tree_id].multiply(
                    n_prev).at[cur_tree_id].add(const) \
                    .at[cur_tree_id].multiply(1.0 / (n_prev + 1.0))
                for vs in self.valid_sets:
                    vs.score = vs.score.at[cur_tree_id].multiply(n_prev) \
                        .at[cur_tree_id].add(const) \
                        .at[cur_tree_id].multiply(1.0 / (n_prev + 1.0))
            self.models.append(host)
            self._invalidate_device_trees()
        self.iter_ += 1
        return False

    def _renew_tree_output(self, tree, row_leaf, mask, cur_tree_id):
        """RF renews against the constant init score, not the running score
        (reference: rf.hpp residual_getter)."""
        obj = self.objective
        if obj is None or not obj.renew_leaves:
            return tree
        from ..ops.renew import renew_leaf_quantile
        residual = obj.label - self._init_scores[cur_tree_id]
        w = mask if self.row_weight is None else mask * self.row_weight
        rung = self.grower_params.num_leaves   # rung-sized leaf arrays
        renewed = renew_leaf_quantile(
            residual, w, row_leaf, rung, float(obj.renew_alpha))
        live = jnp.arange(rung) < tree.num_leaves
        return tree._replace(leaf_value=jnp.where(live, renewed, tree.leaf_value))
