"""Row-sampling strategies: bagging and GOSS, computed on device.

TPU-native re-design of the reference's SampleStrategy
(reference: include/LightGBM/sample_strategy.h:31, BaggingSampleStrategy
src/boosting/bagging.hpp:14, GOSSStrategy src/boosting/goss.hpp:18, factory
src/boosting/sample_strategy.cpp).

The reference materializes compacted ``bag_data_indices`` and copies gradients;
with static shapes on TPU a dense ``[N]`` {0,1} mask is multiplied into
grad/hess/count channels instead — no compaction, no copies, and the same mask
flows straight into the histogram contraction (ops/histogram.py).

Sampling is Bernoulli per row at rate ``bagging_fraction`` (the reference draws
an exact count without replacement — bagging.hpp; the expected in-bag count is
identical and the draw stays on device).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


class SampleStrategy:
    """Produces the per-iteration in-bag mask [N] (float {0,1})."""

    is_hessian_change = False
    # True when the last bag_mask() call drew a NEW bag (vs reusing a cached
    # one) — the compact grower stores reused bags in its permuted row records
    last_fresh = False

    def __init__(self, config, num_data: int, metadata=None):
        self.config = config
        self.num_data = num_data
        self.metadata = metadata

    def bag_mask(self, iter_num: int, grad, hess) -> Optional[jax.Array]:
        """Return in-bag mask for this iteration, or None for 'use all rows'.
        ``grad``/``hess`` are [K, N] (needed by GOSS only)."""
        return None

    def scale_grad_hess(self, mask, grad, hess):
        """GOSS amplifies sampled small-gradient rows; bagging does not."""
        return grad, hess


class BaggingStrategy(SampleStrategy):
    """(reference: BaggingSampleStrategy, src/boosting/bagging.hpp:14)"""

    def __init__(self, config, num_data: int, metadata=None):
        super().__init__(config, num_data, metadata)
        self.fraction = float(config.get("bagging_fraction", 1.0))
        self.pos_fraction = float(config.get("pos_bagging_fraction", 1.0))
        self.neg_fraction = float(config.get("neg_bagging_fraction", 1.0))
        self.freq = int(config.get("bagging_freq", 0))
        self.seed = int(config.get("bagging_seed", 3))
        self.by_query = bool(config.get("bagging_by_query", False))
        self.balanced = self.pos_fraction < 1.0 or self.neg_fraction < 1.0
        self.enabled = self.freq > 0 and (self.fraction < 1.0 or self.balanced)
        self._cached = None
        self._label01 = None
        self._row_query = None
        if self.enabled and self.balanced and metadata is not None \
                and metadata.label is not None:
            self._label01 = jnp.asarray(np.asarray(metadata.label) > 0)
        if self.enabled and self.by_query and metadata is not None \
                and metadata.query_boundaries is not None:
            qb = np.asarray(metadata.query_boundaries)
            rq = np.zeros(num_data, dtype=np.int32)
            for i in range(len(qb) - 1):
                rq[qb[i]:qb[i + 1]] = i
            self._row_query = jnp.asarray(rq)
            self._num_queries = len(qb) - 1

    def bag_mask(self, iter_num, grad, hess):
        self.last_fresh = False
        if not self.enabled:
            return None
        if iter_num % self.freq != 0 and self._cached is not None:
            return self._cached
        self.last_fresh = True
        key = jax.random.PRNGKey(self.seed + iter_num // max(self.freq, 1))
        if self.by_query and self._row_query is not None:
            qkeep = jax.random.uniform(key, (self._num_queries,)) < self.fraction
            mask = qkeep[self._row_query].astype(jnp.float32)
        elif self.balanced and self._label01 is not None:
            u = jax.random.uniform(key, (self.num_data,))
            rate = jnp.where(self._label01, self.pos_fraction, self.neg_fraction)
            mask = (u < rate).astype(jnp.float32)
        else:
            u = jax.random.uniform(key, (self.num_data,))
            mask = (u < self.fraction).astype(jnp.float32)
        self._cached = mask
        return mask


class GOSSStrategy(SampleStrategy):
    """Gradient-based one-side sampling (reference: GOSSStrategy,
    src/boosting/goss.hpp:18): keep the top ``top_rate`` rows by gradient
    magnitude, Bernoulli-sample the rest at ``other_rate/(1-top_rate)`` and
    amplify their grad/hess by ``(1-top_rate)/other_rate``."""

    is_hessian_change = True

    def __init__(self, config, num_data: int, metadata=None):
        super().__init__(config, num_data, metadata)
        self.top_rate = float(config.get("top_rate", 0.2))
        self.other_rate = float(config.get("other_rate", 0.1))
        self.seed = int(config.get("bagging_seed", 3))
        self.learning_rate = float(config.get("learning_rate", 0.1))
        self._amplify = None

    def bag_mask(self, iter_num, grad, hess):
        # warm-up: no sampling for the first 1/learning_rate iterations
        # (reference: goss.hpp Bagging's early return)
        self.last_fresh = False
        if iter_num < int(1.0 / max(self.learning_rate, 1e-12)):
            self._amplify = None
            return None
        self.last_fresh = True
        # multiclass: magnitude summed over class rows (reference sums |g|*h)
        mag = jnp.sum(jnp.abs(grad) * hess, axis=0)
        thresh = jnp.quantile(mag, 1.0 - self.top_rate)
        is_top = mag >= thresh
        key = jax.random.PRNGKey(self.seed + iter_num)
        keep_rate = self.other_rate / max(1.0 - self.top_rate, 1e-12)
        u = jax.random.uniform(u_key := key, (self.num_data,))
        sampled = (~is_top) & (u < keep_rate)
        mask = (is_top | sampled).astype(jnp.float32)
        amp = (1.0 - self.top_rate) / max(self.other_rate, 1e-12)
        self._amplify = jnp.where(sampled, amp, 1.0)
        return mask

    def scale_grad_hess(self, mask, grad, hess):
        if self._amplify is None:
            return grad, hess
        a = self._amplify[None, :]
        return grad * a, hess * a


def create_sample_strategy(config, num_data: int, metadata=None) -> SampleStrategy:
    """(reference: SampleStrategy::CreateSampleStrategy,
    src/boosting/sample_strategy.cpp)"""
    strategy = str(config.get("data_sample_strategy", "bagging")).lower()
    boosting = str(config.get("boosting", "gbdt")).lower()
    if strategy == "goss" or boosting == "goss":
        return GOSSStrategy(config, num_data, metadata)
    return BaggingStrategy(config, num_data, metadata)
