"""Objective functions (gradient/hessian kernels), computed on device.

TPU-native re-design of the reference's objective layer
(reference: include/LightGBM/objective_function.h, factory
ObjectiveFunction::CreateObjectiveFunction src/objective/objective_function.cpp:12-130,
families in src/objective/{regression,binary,multiclass,rank,xentropy}_objective.hpp
and their CUDA mirrors src/objective/cuda/*).

Where the reference launches per-row CUDA kernels, here every objective is a pure
jnp function over the score vector — XLA fuses the elementwise math into the
surrounding training step, and the same code runs under ``shard_map`` for
data-parallel training (per-query ranking reductions become segment ops over
padded query blocks).

Interface mirrors the reference's (objective_function.h):
  * ``get_gradients(score) -> (grad, hess)``     (GetGradients, :37)
  * ``boost_from_score(class_id)``               (BoostFromScore)
  * ``convert_output(raw)``                      (ConvertOutput, :81)
  * ``renew_tree_output`` percentile/leaf renewal (RenewTreeOutput, :57)
  * ``num_model_per_iteration``                  (multiclass: num_class trees/iter)
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_EPS = 1e-15


def _np(x):
    return np.asarray(x)


class Objective:
    """Base objective (reference: ObjectiveFunction, objective_function.h)."""

    name = "custom"
    is_constant_hessian = False
    num_model_per_iteration = 1
    # leaves renewed after growth (reference: RegressionL1loss::RenewTreeOutput)
    renew_leaves = False
    is_ranking = False
    # gradients depend only on this row's (label, weight, scores) — required
    # by the compact grower, whose rows live in a per-tree permuted order
    row_elementwise = True

    def __init__(self, config):
        self.config = config

    def init(self, metadata, num_data: int) -> None:
        self.num_data = num_data
        self.label = jnp.asarray(metadata.label, jnp.float32)
        self.weight = (
            jnp.asarray(metadata.weight, jnp.float32)
            if metadata.weight is not None else None
        )
        self.metadata = metadata

    def _weighted(self, grad, hess):
        if self.weight is not None:
            return grad * self.weight, hess * self.weight
        return grad, hess

    def get_gradients(self, score: jax.Array) -> Tuple[jax.Array, jax.Array]:
        raise NotImplementedError

    def boost_from_score(self, class_id: int = 0) -> float:
        return 0.0

    def convert_output(self, raw: jax.Array) -> jax.Array:
        return raw

    def renew_tree_output(self, score, residual_fn=None):
        raise NotImplementedError

    def _avg_label(self) -> float:
        lbl = _np(self.label).astype(np.float64)
        if self.weight is not None:
            w = _np(self.weight).astype(np.float64)
            return float((lbl * w).sum() / max(w.sum(), _EPS))
        return float(lbl.mean())


# ---------------------------------------------------------------------------
# Regression family (reference: src/objective/regression_objective.hpp)
# ---------------------------------------------------------------------------
class RegressionL2(Objective):
    """L2 loss (reference: RegressionL2loss, regression_objective.hpp:93)."""

    name = "regression"
    is_constant_hessian = True

    def __init__(self, config):
        super().__init__(config)
        self.sqrt = bool(config.get("reg_sqrt", False))

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if self.sqrt:
            lbl = self.label
            self.label = jnp.sign(lbl) * jnp.sqrt(jnp.abs(lbl))

    def get_gradients(self, score):
        grad = score - self.label
        hess = jnp.ones_like(score)
        return self._weighted(grad, hess)

    def boost_from_score(self, class_id: int = 0) -> float:
        return self._avg_label()

    def convert_output(self, raw):
        if self.sqrt:
            return jnp.sign(raw) * raw * raw
        return raw


class RegressionL1(RegressionL2):
    """L1 loss; leaf outputs renewed to the per-leaf weighted median of residuals
    (reference: RegressionL1loss, regression_objective.hpp:165)."""

    name = "regression_l1"
    is_constant_hessian = True
    renew_leaves = True
    renew_alpha = 0.5

    def get_gradients(self, score):
        diff = score - self.label
        grad = jnp.sign(diff)
        hess = jnp.ones_like(score)
        return self._weighted(grad, hess)


class RegressionHuber(RegressionL2):
    """Huber loss (reference: RegressionHuberLoss, regression_objective.hpp:234)."""

    name = "huber"
    is_constant_hessian = True

    def __init__(self, config):
        super().__init__(config)
        self.alpha = float(config.get("alpha", 0.9))

    def get_gradients(self, score):
        diff = score - self.label
        grad = jnp.where(jnp.abs(diff) <= self.alpha, diff,
                         jnp.sign(diff) * self.alpha)
        hess = jnp.ones_like(score)
        return self._weighted(grad, hess)


class RegressionFair(RegressionL2):
    """Fair loss (reference: RegressionFairLoss, regression_objective.hpp:290)."""

    name = "fair"
    is_constant_hessian = False

    def __init__(self, config):
        super().__init__(config)
        self.c = float(config.get("fair_c", 1.0))

    def get_gradients(self, score):
        diff = score - self.label
        c = self.c
        grad = c * diff / (jnp.abs(diff) + c)
        hess = c * c / ((jnp.abs(diff) + c) ** 2)
        return self._weighted(grad, hess)


class RegressionPoisson(RegressionL2):
    """Poisson regression on log-link scores
    (reference: RegressionPoissonLoss, regression_objective.hpp:341)."""

    name = "poisson"
    is_constant_hessian = False

    def __init__(self, config):
        super().__init__(config)
        self.max_delta = float(config.get("poisson_max_delta_step", 0.7))

    def get_gradients(self, score):
        ex = jnp.exp(score)
        grad = ex - self.label
        hess = jnp.exp(score + self.max_delta)
        return self._weighted(grad, hess)

    def boost_from_score(self, class_id: int = 0) -> float:
        return float(np.log(max(self._avg_label(), _EPS)))

    def convert_output(self, raw):
        return jnp.exp(raw)


class RegressionQuantile(RegressionL2):
    """Quantile (pinball) loss with per-leaf quantile renewal
    (reference: RegressionQuantileloss, regression_objective.hpp:417)."""

    name = "quantile"
    is_constant_hessian = True
    renew_leaves = True

    def __init__(self, config):
        super().__init__(config)
        self.alpha = float(config.get("alpha", 0.9))
        self.renew_alpha = self.alpha

    def get_gradients(self, score):
        diff = score - self.label
        grad = jnp.where(diff >= 0, 1.0 - self.alpha, -self.alpha)
        hess = jnp.ones_like(score)
        return self._weighted(grad, hess)


class RegressionMAPE(RegressionL2):
    """MAPE loss (reference: RegressionMAPELOSS, regression_objective.hpp:498)."""

    name = "mape"
    is_constant_hessian = True
    renew_leaves = True
    renew_alpha = 0.5

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        # label_weight = 1 / max(1, |label|), folded into the row weight
        lw = 1.0 / jnp.maximum(1.0, jnp.abs(self.label))
        self.weight = lw if self.weight is None else self.weight * lw

    def get_gradients(self, score):
        diff = score - self.label
        grad = jnp.sign(diff)
        hess = jnp.ones_like(score)
        return self._weighted(grad, hess)


class RegressionGamma(RegressionPoisson):
    """Gamma deviance on log-link scores
    (reference: RegressionGammaLoss, regression_objective.hpp:578)."""

    name = "gamma"

    def get_gradients(self, score):
        e = jnp.exp(-score)
        grad = 1.0 - self.label * e
        hess = self.label * e
        return self._weighted(grad, hess)


class RegressionTweedie(RegressionPoisson):
    """Tweedie deviance on log-link scores
    (reference: RegressionTweedieLoss, regression_objective.hpp:612)."""

    name = "tweedie"

    def __init__(self, config):
        super().__init__(config)
        self.rho = float(config.get("tweedie_variance_power", 1.5))

    def get_gradients(self, score):
        rho = self.rho
        e1 = jnp.exp((1.0 - rho) * score)
        e2 = jnp.exp((2.0 - rho) * score)
        grad = -self.label * e1 + e2
        hess = -self.label * (1.0 - rho) * e1 + (2.0 - rho) * e2
        return self._weighted(grad, hess)


# ---------------------------------------------------------------------------
# Binary (reference: src/objective/binary_objective.hpp:21 BinaryLogloss)
# ---------------------------------------------------------------------------
class BinaryLogloss(Objective):
    name = "binary"

    def __init__(self, config):
        super().__init__(config)
        self.sigmoid = float(config.get("sigmoid", 1.0))
        self.is_unbalance = bool(config.get("is_unbalance", False))
        self.scale_pos_weight = float(config.get("scale_pos_weight", 1.0))

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lbl = _np(metadata.label)
        uniq = np.unique(lbl)
        if not np.all(np.isin(uniq, [0.0, 1.0])):
            raise ValueError("binary objective requires labels in {0, 1}")
        if metadata.weight is not None:
            w = _np(metadata.weight).astype(np.float64)
            pos = float(w[lbl > 0].sum())
            neg = float(w.sum() - pos)
        else:
            pos = float((lbl > 0).sum())
            neg = float(len(lbl) - pos)
        self.label01 = jnp.asarray(lbl > 0, jnp.float32)
        # class weighting (reference: binary_objective.hpp:60-86 — the
        # MINORITY class is upweighted to majority/minority, the other stays 1)
        if self.is_unbalance and pos > 0 and neg > 0:
            if pos > neg:
                self.label_weights = (pos / neg, 1.0)   # (neg_w, pos_w)
            else:
                self.label_weights = (1.0, neg / pos)
        else:
            self.label_weights = (1.0, self.scale_pos_weight)
        self._pos, self._neg = pos, neg

    def get_gradients(self, score):
        sig = self.sigmoid
        # derived inline from self.label: the compact grower rebinds label
        # per-tree (rows live in a permuted order), so gradients may depend
        # only on self.label / self.weight (see Objective.row_elementwise)
        y = (self.label > 0).astype(jnp.float32)
        p = jax.nn.sigmoid(sig * score)
        neg_w, pos_w = self.label_weights
        w = jnp.where(y > 0, pos_w, neg_w)
        grad = (p - y) * sig * w
        hess = p * (1.0 - p) * sig * sig * w
        return self._weighted(grad, hess)

    def boost_from_score(self, class_id: int = 0) -> float:
        # sigmoid^-1 of weighted positive rate (reference: binary_objective.hpp:94-108)
        if self.weight is not None:
            w = _np(self.weight).astype(np.float64)
            lbl = _np(self.label01).astype(np.float64)
            pavg = float((lbl * w).sum() / max(w.sum(), _EPS))
        else:
            pavg = self._pos / max(self._pos + self._neg, 1.0)
        pavg = min(max(pavg, 1e-15), 1.0 - 1e-15)
        return float(np.log(pavg / (1.0 - pavg)) / self.sigmoid)

    def convert_output(self, raw):
        return jax.nn.sigmoid(self.sigmoid * raw)


# ---------------------------------------------------------------------------
# Multiclass (reference: src/objective/multiclass_objective.hpp)
# ---------------------------------------------------------------------------
class MulticlassSoftmax(Objective):
    """Softmax over num_class score rows (reference: MulticlassSoftmax,
    multiclass_objective.hpp:24). One tree per class per iteration."""

    name = "multiclass"

    def __init__(self, config):
        super().__init__(config)
        self.num_class = int(config.get("num_class", 1))
        if self.num_class <= 1:
            raise ValueError("multiclass objective requires num_class > 1")
        self.num_model_per_iteration = self.num_class

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lbl = _np(metadata.label).astype(np.int32)
        if lbl.min() < 0 or lbl.max() >= self.num_class:
            raise ValueError(
                f"multiclass labels must be in [0, {self.num_class}); "
                f"got range [{lbl.min()}, {lbl.max()}]")
        self._class_counts = np.bincount(lbl, minlength=self.num_class)

    def get_gradients(self, score):
        # score: [K, N]; one-hot derived inline from self.label (see
        # Objective.row_elementwise — the compact grower rebinds label)
        p = jax.nn.softmax(score, axis=0)                   # [K, N]
        classes = jnp.arange(self.num_class, dtype=jnp.float32)
        y = (self.label[None, :] == classes[:, None]).astype(jnp.float32)
        grad = p - y
        factor = self.num_class / (self.num_class - 1.0)
        hess = factor * p * (1.0 - p)
        if self.weight is not None:
            grad = grad * self.weight[None, :]
            hess = hess * self.weight[None, :]
        return grad, hess

    def boost_from_score(self, class_id: int = 0) -> float:
        # reference inits multiclass scores at 0 (softmax handles normalization)
        return 0.0

    def convert_output(self, raw):
        # raw: [..., K] -> probabilities
        return jax.nn.softmax(raw, axis=-1)


class MulticlassOVA(Objective):
    """One-vs-all: num_class independent sigmoid losses
    (reference: MulticlassOVA, multiclass_objective.hpp:186)."""

    name = "multiclassova"

    def __init__(self, config):
        super().__init__(config)
        self.num_class = int(config.get("num_class", 1))
        if self.num_class <= 1:
            raise ValueError("multiclassova requires num_class > 1")
        self.num_model_per_iteration = self.num_class
        self.sigmoid = float(config.get("sigmoid", 1.0))

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lbl = _np(metadata.label).astype(np.int32)
        self._class_rates = (
            np.bincount(lbl, minlength=self.num_class) / max(len(lbl), 1))

    def get_gradients(self, score):
        sig = self.sigmoid
        classes = jnp.arange(self.num_class, dtype=jnp.float32)
        y = (self.label[None, :] == classes[:, None]).astype(jnp.float32)
        p = jax.nn.sigmoid(sig * score)
        grad = (p - y) * sig
        hess = p * (1.0 - p) * sig * sig
        if self.weight is not None:
            grad = grad * self.weight[None, :]
            hess = hess * self.weight[None, :]
        return grad, hess

    def boost_from_score(self, class_id: int = 0) -> float:
        pavg = min(max(float(self._class_rates[class_id]), 1e-15), 1 - 1e-15)
        return float(np.log(pavg / (1.0 - pavg)) / self.sigmoid)

    def convert_output(self, raw):
        return jax.nn.sigmoid(self.sigmoid * raw)


# ---------------------------------------------------------------------------
# Cross-entropy on continuous labels in [0,1]
# (reference: src/objective/xentropy_objective.hpp:44,:185)
# ---------------------------------------------------------------------------
class CrossEntropy(Objective):
    name = "cross_entropy"

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        lbl = _np(metadata.label)
        if lbl.min() < 0 or lbl.max() > 1:
            raise ValueError("cross_entropy labels must lie in [0, 1]")

    def get_gradients(self, score):
        p = jax.nn.sigmoid(score)
        grad = p - self.label
        hess = p * (1.0 - p)
        return self._weighted(grad, hess)

    def boost_from_score(self, class_id: int = 0) -> float:
        pavg = min(max(self._avg_label(), 1e-15), 1 - 1e-15)
        return float(np.log(pavg / (1.0 - pavg)))

    def convert_output(self, raw):
        return jax.nn.sigmoid(raw)


class CrossEntropyLambda(Objective):
    """Alternative parametrization (reference: CrossEntropyLambda,
    xentropy_objective.hpp:185)."""

    name = "cross_entropy_lambda"

    def get_gradients(self, score):
        # z = log1p(exp(score)); loss = (1-y)*score ... reference parametrization
        epf = jnp.exp(score)
        hhat = jnp.log1p(epf)
        z = 1.0 - jnp.exp(-hhat)
        enf = jnp.exp(-score)
        grad = (1.0 - self.label / jnp.maximum(z, _EPS)) / (1.0 + enf)
        c = 1.0 / (1.0 - jnp.exp(-epf))
        hess = epf / ((1.0 + epf) ** 2) * (
            1.0 + self.label * (1.0 - c + epf * c * c) / jnp.maximum(z * z, _EPS) * z)
        # guard numerical blowups near score -> -inf
        grad = jnp.nan_to_num(grad, nan=0.0, posinf=0.0, neginf=0.0)
        hess = jnp.clip(jnp.nan_to_num(hess, nan=1.0, posinf=1.0, neginf=_EPS),
                        _EPS, None)
        return self._weighted(grad, hess)

    def boost_from_score(self, class_id: int = 0) -> float:
        avg = max(self._avg_label(), 1e-15)
        return float(np.log(np.expm1(avg)) if avg < 30 else avg)

    def convert_output(self, raw):
        return jnp.log1p(jnp.exp(raw))


# ---------------------------------------------------------------------------
# Ranking (reference: src/objective/rank_objective.hpp — LambdarankNDCG :138,
# RankXENDCG :378; CUDA mirror cuda_rank_objective.cu)
# ---------------------------------------------------------------------------
def _pad_queries(boundaries: np.ndarray) -> Tuple[np.ndarray, int]:
    """Build a [Q, M] row-index matrix (padded with -1) from query
    boundaries — vectorized (no O(total rows) Python loop)."""
    sizes = np.diff(boundaries)
    q = len(sizes)
    m = int(sizes.max()) if q else 1
    pos = np.arange(m, dtype=np.int32)[None, :]
    idx = boundaries[:-1, None].astype(np.int32) + pos
    return np.where(pos < sizes[:, None], idx, -1), m


class LambdarankNDCG(Objective):
    row_elementwise = False
    """LambdaRank with |ΔNDCG| weighting.

    The reference computes per-query lambda gradients with a sorted-document scan
    (rank_objective.hpp:138-320; on device via bitonic sort in
    cuda_rank_objective.cu). Here queries are padded to a [Q, M] matrix, scores
    are sorted per query with ``jnp.argsort`` (XLA sort), and the full M×M pair
    matrix is evaluated with masks — MXU/VPU-friendly, no data-dependent shapes.
    """

    name = "lambdarank"
    is_ranking = True

    def __init__(self, config):
        super().__init__(config)
        self.sigmoid = float(config.get("sigmoid", 2.0))
        self.norm = bool(config.get("lambdarank_norm", True))
        trunc = int(config.get("lambdarank_truncation_level", 30))
        self.truncation_level = trunc
        self.label_gain = config.get("label_gain", None)
        # position-bias correction (reference: RankingObjective pos_biases_,
        # rank_objective.hpp:56-98 + UpdatePositionBiasFactors :296)
        self.bias_reg = float(config.get(
            "lambdarank_position_bias_regularization", 0.0))
        self.bias_lr = float(config.get("learning_rate", 0.1))

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            raise ValueError("ranking objective requires query groups (set_group)")
        qb = metadata.query_boundaries
        idx, m = _pad_queries(qb)
        self.query_index = jnp.asarray(idx)          # [Q, M]
        self.query_mask = jnp.asarray(idx >= 0)      # [Q, M]
        self.max_query = m
        lbl = _np(metadata.label).astype(np.int32)
        max_label = int(lbl.max()) if len(lbl) else 0
        if self.label_gain is None:
            gains = (2.0 ** np.arange(max(max_label + 1, 2))) - 1.0
        else:
            gains = np.asarray(self.label_gain, dtype=np.float64)
            if len(gains) <= max_label:
                raise ValueError("label_gain shorter than max label + 1")
        self._label_gain_table = gains
        # per-row gain values, padded gather-safe
        row_gain = gains[lbl]
        self.row_gain = jnp.asarray(row_gain, jnp.float32)
        self.row_label = jnp.asarray(lbl, jnp.int32)
        # inverse max DCG per query, vectorized over the padded query matrix
        # (reference: lambdarank_ndcg init)
        gp = np.where(idx >= 0, row_gain[np.maximum(idx, 0)], -np.inf)
        gp = -np.sort(-gp, axis=1)                           # desc per query
        k = min(m, self.truncation_level)
        disc = 1.0 / np.log2(np.arange(k) + 2.0)
        mdcg = np.sum(np.where(np.isfinite(gp[:, :k]), gp[:, :k], 0.0)
                      * disc[None, :], axis=1)
        self.inv_max_dcg = jnp.asarray(
            np.where(mdcg > 0, 1.0 / np.maximum(mdcg, 1e-300), 0.0),
            jnp.float32)                                     # [Q]
        # per-position bias state (updated every iteration -> the gradient
        # fn must not be jit-frozen; see is_stochastic)
        self.positions = None
        if metadata.position is not None:
            pos = np.asarray(metadata.position).astype(np.int32)
            if len(pos) != num_data:
                raise ValueError("position length != num_data")
            self.positions = jnp.asarray(pos)
            self.num_position_ids = int(pos.max()) + 1
            self.pos_biases = jnp.zeros((self.num_position_ids,), jnp.float32)
            # padding rows carry zero weight (gbdt._pad_metadata) and must
            # not count toward the per-position regularizer
            wts = (np.asarray(metadata.weight, np.float64)
                   if metadata.weight is not None else np.ones(num_data))
            self._pos_counts = jnp.asarray(
                np.bincount(pos, weights=(wts > 0).astype(np.float64),
                            minlength=self.num_position_ids)
                .astype(np.float32))
            self.is_stochastic = True  # stateful bias updates each call

    # queries processed in chunks of this many per pair-tensor block; the
    # block is [CHUNK, T, M] floats — memory stays bounded for MS-LTR-scale
    # datasets (the old formulation materialized [Q, M, M])
    _QUERY_CHUNK = 256

    def _query_chunk_grads(self, s, g, mask, inv_max_dcg):
        """Lambda gradients for one chunk of padded queries [Qc, M].

        The reference enumerates pairs (i, j) over SORTED positions with
        i < truncation_level and j > i (rank_objective.hpp:222-257) — a
        [T, M] pair block per query, not [M, M]."""
        qc, m = s.shape
        t = min(self.truncation_level, m)
        sig = self.sigmoid

        order = jnp.argsort(-s, axis=1)                      # [Qc, M]
        rank_of = jnp.argsort(order, axis=1)
        s_s = jnp.take_along_axis(s, order, axis=1)
        g_s = jnp.take_along_axis(g, order, axis=1)
        m_s = jnp.take_along_axis(mask, order, axis=1)
        disc = 1.0 / jnp.log2(jnp.arange(m, dtype=jnp.float32) + 2.0)  # [M]

        # pair block [Qc, T, M]: i = sorted position < T, j = any position > i
        s_i = s_s[:, :t, None]
        s_j = s_s[:, None, :]
        g_i = g_s[:, :t, None]
        g_j = g_s[:, None, :]
        d_i = disc[None, :t, None]
        d_j = disc[None, None, :]
        upper = jnp.arange(t)[:, None] < jnp.arange(m)[None, :]
        pair_valid = (m_s[:, :t, None] & m_s[:, None, :]
                      & (g_i != g_j) & upper[None])
        delta_ndcg = jnp.abs((g_i - g_j) * (d_i - d_j)) \
            * inv_max_dcg[:, None, None]
        # lambda applies to the HIGHER-labeled doc of the pair
        i_high = g_i > g_j
        ds_high = jnp.where(i_high, s_i - s_j, s_j - s_i)
        if self.norm:
            # score-distance regularization (reference: "regular the
            # delta_pair_NDCG by score distance",
            # rank_objective.hpp:242-244): applied when the query's best
            # and worst scores differ
            n_valid = jnp.sum(m_s.astype(jnp.int32), axis=1)
            best = s_s[:, 0]
            worst = jnp.take_along_axis(
                s_s, jnp.maximum(n_valid - 1, 0)[:, None], axis=1)[:, 0]
            delta_ndcg = jnp.where(
                (best != worst)[:, None, None],
                delta_ndcg / (0.01 + jnp.abs(ds_high)), delta_ndcg)
        p = jax.nn.sigmoid(sig * ds_high)
        lam_h = sig * (p - 1.0) * delta_ndcg           # <= 0, on higher doc
        hes = sig * sig * p * (1.0 - p) * delta_ndcg
        lam_h = jnp.where(pair_valid, lam_h, 0.0)
        hes = jnp.where(pair_valid, hes, 0.0)

        lam_i = jnp.where(i_high, lam_h, -lam_h)       # contribution @ pos i
        pad_t = ((0, 0), (0, m - t))
        grad_sorted = jnp.pad(lam_i.sum(axis=2), pad_t) - lam_i.sum(axis=1)
        hess_sorted = jnp.pad(hes.sum(axis=2), pad_t) + hes.sum(axis=1)

        if self.norm:
            # reference norm_ (rank_objective.hpp:259-263)
            sum_lambdas = 2.0 * (-lam_h).sum(axis=(1, 2))
            scale = jnp.where(
                sum_lambdas > 0,
                jnp.log2(1.0 + sum_lambdas) / jnp.maximum(sum_lambdas, _EPS),
                1.0)
            grad_sorted = grad_sorted * scale[:, None]
            hess_sorted = hess_sorted * scale[:, None]

        # back to document order within the query
        grad_q = jnp.take_along_axis(grad_sorted, rank_of, axis=1)
        hess_q = jnp.take_along_axis(hess_sorted, rank_of, axis=1)
        return grad_q, hess_q

    def get_gradients(self, score):
        idx = self.query_index                       # [Q, M]
        mask = self.query_mask
        q, m = idx.shape
        safe_idx = jnp.maximum(idx, 0)
        if self.positions is not None:
            # ranking math sees position-debiased scores (reference:
            # rank_objective.hpp:70 score + pos_biases_[positions_[j]])
            score = score + self.pos_biases[self.positions]
        s = jnp.where(mask, score[safe_idx], -jnp.inf)        # [Q, M]
        g = jnp.where(mask, self.row_gain[safe_idx], 0.0)     # gains

        chunk = min(self._QUERY_CHUNK, q)
        q_pad = (-q) % chunk
        if q_pad:
            s = jnp.pad(s, ((0, q_pad), (0, 0)), constant_values=-jnp.inf)
            g = jnp.pad(g, ((0, q_pad), (0, 0)))
            mask_p = jnp.pad(mask, ((0, q_pad), (0, 0)))
            imd = jnp.pad(self.inv_max_dcg, (0, q_pad))
        else:
            mask_p = mask
            imd = self.inv_max_dcg
        n_chunks = (q + q_pad) // chunk

        def one_chunk(args):
            sc, gc, mc, imdc = args
            return self._query_chunk_grads(sc, gc, mc, imdc)

        grad_q, hess_q = jax.lax.map(
            one_chunk,
            (s.reshape(n_chunks, chunk, m), g.reshape(n_chunks, chunk, m),
             mask_p.reshape(n_chunks, chunk, m),
             imd.reshape(n_chunks, chunk)))
        grad_q = grad_q.reshape(-1, m)[:q]
        hess_q = hess_q.reshape(-1, m)[:q]

        grad = jnp.zeros_like(score).at[safe_idx.reshape(-1)].add(
            jnp.where(mask, grad_q, 0.0).reshape(-1))
        hess = jnp.zeros_like(score).at[safe_idx.reshape(-1)].add(
            jnp.where(mask, hess_q, 0.0).reshape(-1))
        grad, hess = self._weighted(grad, hess)
        if self.positions is not None:
            # Newton step on the per-position bias factors (reference:
            # UpdatePositionBiasFactors, rank_objective.hpp:296-331, fed the
            # weight-multiplied lambdas — hence after _weighted)
            p_ids = self.positions
            d1 = jnp.zeros((self.num_position_ids,)).at[p_ids].add(-grad)
            d2 = jnp.zeros((self.num_position_ids,)).at[p_ids].add(-hess)
            d1 = d1 - self.pos_biases * self.bias_reg * self._pos_counts
            d2 = d2 - self.bias_reg * self._pos_counts
            self.pos_biases = self.pos_biases + \
                self.bias_lr * d1 / (jnp.abs(d2) + 0.001)
        return grad, hess


class RankXENDCG(Objective):
    """Listwise cross-entropy surrogate for NDCG
    (reference: RankXENDCG, rank_objective.hpp:378)."""

    name = "rank_xendcg"
    is_ranking = True
    row_elementwise = False
    # draws fresh gamma noise each iteration — must not be jit-frozen
    is_stochastic = True

    def __init__(self, config):
        super().__init__(config)
        self.seed = int(config.get("objective_seed", 5) or 5)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            raise ValueError("ranking objective requires query groups (set_group)")
        idx, m = _pad_queries(metadata.query_boundaries)
        self.query_index = jnp.asarray(idx)
        self.query_mask = jnp.asarray(idx >= 0)
        lbl = _np(metadata.label).astype(np.float64)
        phi = (2.0 ** lbl) - 1.0
        self.row_phi = jnp.asarray(phi, jnp.float32)
        self._key = jax.random.PRNGKey(self.seed)

    def get_gradients(self, score):
        idx = self.query_index
        mask = self.query_mask
        safe_idx = jnp.maximum(idx, 0)
        s = jnp.where(mask, score[safe_idx], -jnp.inf)
        phi = jnp.where(mask, self.row_phi[safe_idx], 0.0)
        # gumbel-perturbed relevance target (reference draws per-doc gammas)
        self._key, sub = jax.random.split(self._key)
        gam = jax.random.gamma(sub, 1.0, shape=phi.shape)
        rho_raw = phi / jnp.maximum(gam, _EPS)
        denom = jnp.where(mask, rho_raw, 0.0).sum(axis=1, keepdims=True)
        t = rho_raw / jnp.maximum(denom, _EPS)       # target distribution
        p = jax.nn.softmax(s, axis=1)
        p = jnp.where(mask, p, 0.0)
        grad_q = p - jnp.where(mask, t, 0.0)
        hess_q = p * (1.0 - p)
        grad = jnp.zeros_like(score).at[safe_idx.reshape(-1)].add(
            jnp.where(mask, grad_q, 0.0).reshape(-1))
        hess = jnp.zeros_like(score).at[safe_idx.reshape(-1)].add(
            jnp.where(mask, hess_q, 0.0).reshape(-1))
        hess = jnp.maximum(hess, _EPS)
        return self._weighted(grad, hess)


# ---------------------------------------------------------------------------
# Factory (reference: ObjectiveFunction::CreateObjectiveFunction,
# src/objective/objective_function.cpp:12-130)
# ---------------------------------------------------------------------------
_OBJECTIVES = {
    "regression": RegressionL2,
    "regression_l2": RegressionL2,
    "l2": RegressionL2,
    "mean_squared_error": RegressionL2,
    "mse": RegressionL2,
    "l2_root": RegressionL2,
    "root_mean_squared_error": RegressionL2,
    "rmse": RegressionL2,
    "regression_l1": RegressionL1,
    "l1": RegressionL1,
    "mean_absolute_error": RegressionL1,
    "mae": RegressionL1,
    "huber": RegressionHuber,
    "fair": RegressionFair,
    "poisson": RegressionPoisson,
    "quantile": RegressionQuantile,
    "mape": RegressionMAPE,
    "mean_absolute_percentage_error": RegressionMAPE,
    "gamma": RegressionGamma,
    "tweedie": RegressionTweedie,
    "binary": BinaryLogloss,
    "multiclass": MulticlassSoftmax,
    "softmax": MulticlassSoftmax,
    "multiclassova": MulticlassOVA,
    "multiclass_ova": MulticlassOVA,
    "ova": MulticlassOVA,
    "ovr": MulticlassOVA,
    "cross_entropy": CrossEntropy,
    "xentropy": CrossEntropy,
    "cross_entropy_lambda": CrossEntropyLambda,
    "xentlambda": CrossEntropyLambda,
    "lambdarank": LambdarankNDCG,
    "rank_xendcg": RankXENDCG,
    "xendcg": RankXENDCG,
    "xe_ndcg": RankXENDCG,
    "xe_ndcg_mart": RankXENDCG,
    "xendcg_mart": RankXENDCG,
}


def create_objective(name: str, config) -> Optional[Objective]:
    """Create an objective by (aliased) name; None for 'custom'/'none'."""
    if name is None or name in ("custom", "none", "null", "na"):
        return None
    key = str(name).lower()
    if key not in _OBJECTIVES:
        raise ValueError(f"Unknown objective: {name}")
    return _OBJECTIVES[key](config)
