"""Serving-quality observability: drift monitors, latency attribution,
SLO burn-rate tracking (ROADMAP 4's "observe" pillar).

The continuous-learning loop (train -> deploy -> observe -> refit ->
hot-swap) needs a machine-readable signal that a deployed model has gone
stale or its traffic has shifted. The reference ships that feedback
surface for training (``src/metric/``, ``GBDT::ValidOneIter``); this
module is its serving-side analogue, built on the device-resident
featurization of the serving hot path: every served request is already
binned ON DEVICE, so per-feature bin-occupancy — the raw material of
covariate-drift detection — accumulates with pure on-device adds inside
the existing ``serve_tick`` span, at zero extra host transfers.

Three planes, one owner (:class:`ServingObserver`, held by a
PredictionServer):

* **Drift** (:class:`DriftMonitor`) — at attach time the model ships its
  reference distributions: the training data's normalized per-feature
  bin occupancy (``BinnedDataset.reference_bin_distribution``) and a
  fixed-edge histogram of the training raw margins. Each served batch's
  binned matrix folds into a device ``[F*B]`` occupancy accumulator and
  each predict batch's raw margins into a ``[K, SB]`` score accumulator
  (one jitted scatter-add per warmed rung, pre-lowered by
  :meth:`DriftMonitor.warm` so an armed monitor adds ZERO steady-state
  compiles). Every ``tpu_drift_flush_every`` serving ticks the window
  flushes to host — the ONE declared d2h (``host_syncs`` counts it;
  guard-tested) — and PSI / KL per feature plus score drift are computed
  against the reference. Events are hysteresis-gated: ``drift_detected``
  fires when PSI crosses ``tpu_drift_psi_threshold`` (within one flush
  of a real shift), ``drift_cleared`` only below HALF the threshold, and
  a feature that stays drifted re-fires nothing — no flapping.
* **Latency attribution** — every ServeFuture is stamped with its phase
  times (queue-wait / featurize+dispatch / slice-return) and completed
  requests land in fixed-bucket latency histograms keyed by
  (endpoint kind, model version), exposed as real Prometheus histogram
  series (``lgbm_tpu_serve_latency_ms_bucket{kind=,version=,le=}``).
* **SLO** (:class:`SloTracker`) — a request is "good" when it completes
  within ``tpu_serve_slo_ms``; rolling good/bad counts in 10 s buckets
  feed multi-window (5 m / 1 h) error-budget burn rates
  (``bad_fraction / (1 - tpu_serve_slo_target)``), exposed as gauges
  with ``slo_burn`` flight events on sustained burn > 1 over both
  windows.

Flush records (``drift_flush`` / ``slo``) go to the ``tpu_metrics_path``
stream and compact twins into the flight recorder — ``scripts/obs
drift`` renders the latest flush's PSI table, top drifted features, and
the SLO burn tail jax-free.

The module level is numpy-only (obs/__init__ stays importable without a
backend); jax loads lazily inside the device accumulate builders.
"""
from __future__ import annotations

import bisect
import collections
import functools
import math
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils import log
from . import flight
from . import metrics as obs_metrics

#: probability floor for PSI/KL terms: an empty bin must contribute a
#: large-but-finite term, not an infinity (the conventional PSI floor)
PSI_EPS = 1e-4

#: the score-distribution "feature" name used in drift events/gauges
SCORE_FEATURE = "__score__"

#: fixed latency-histogram bucket upper bounds, milliseconds (Prometheus
#: ``le`` labels; +Inf is implicit)
LATENCY_BUCKETS_MS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                      500.0, 1000.0, 2500.0, 5000.0)

#: cap on per-feature PSI maps embedded in flush records/gauges for very
#: wide datasets (drifted features are always included regardless)
PSI_MAP_CAP = 64
PSI_MAP_FULL_MAX = 256


# -- divergence math (host side, numpy; shared with tests/CLI) --------------
def equal_mass_groups(ref_probs: np.ndarray, n_groups: int) -> np.ndarray:
    """Merge adjacent bins into ~equal reference-mass groups: ``[..., B]``
    probability rows -> ``[..., B]`` int group ids in ``[0, n_groups)``,
    monotone along the bin axis.

    PSI over the raw mapper bins is biased upward: a 255-bin quantile
    mapper holds ~0.4% reference mass per bin, and any finite serving
    window leaves most bins empty, so every empty bin pays the epsilon
    floor penalty and UNSHIFTED traffic reads as drifted. The standard
    construction compares ~10-20 equal-population buckets; grouping by
    cumulative reference mass recovers exactly that from the mapper's
    quantile bins (a feature with fewer bins than groups keeps its bins
    1:1). Bins empty in BOTH distributions then share the floor and
    contribute nothing."""
    p = np.asarray(ref_probs, np.float64)
    cum_before = np.cumsum(p, axis=-1) - p
    return np.minimum((cum_before * n_groups).astype(np.int64),
                      n_groups - 1)


def group_counts(counts: np.ndarray, gid: np.ndarray,
                 n_groups: int) -> np.ndarray:
    """Sum ``[F, B]`` per-bin counts into ``[F, G]`` per-group counts."""
    counts = np.asarray(counts, np.float64)
    f = counts.shape[0]
    flat = gid + np.arange(f, dtype=np.int64)[:, None] * n_groups
    return np.bincount(flat.ravel(), weights=counts.ravel(),
                       minlength=f * n_groups).reshape(f, n_groups)


def psi_rows(ref: np.ndarray, cur: np.ndarray,
             valid: Optional[np.ndarray] = None) -> np.ndarray:
    """Population Stability Index per row: ``sum_b (q-p) * ln(q/p)``.

    ``ref``/``cur`` are ``[..., B]`` probability rows; ``valid`` masks
    the padded bin tail of features with fewer than B bins."""
    p = np.maximum(np.asarray(ref, np.float64), PSI_EPS)
    q = np.maximum(np.asarray(cur, np.float64), PSI_EPS)
    t = (q - p) * np.log(q / p)
    if valid is not None:
        t = np.where(valid, t, 0.0)
    return t.sum(axis=-1)


def kl_rows(ref: np.ndarray, cur: np.ndarray,
            valid: Optional[np.ndarray] = None) -> np.ndarray:
    """``KL(cur || ref)`` per row, with the same floor/mask as PSI."""
    p = np.maximum(np.asarray(ref, np.float64), PSI_EPS)
    q = np.maximum(np.asarray(cur, np.float64), PSI_EPS)
    t = q * np.log(q / p)
    if valid is not None:
        t = np.where(valid, t, 0.0)
    return t.sum(axis=-1)


# -- device accumulate programs (lazy jax; one per (layout, rung)) ----------
@functools.lru_cache(maxsize=None)
def _bin_accum_fn(packed: bool, num_features: int, bin_width: int):
    """Jitted ``occ[F*B] += onehot(bins)`` over the valid row prefix.

    ``bins`` is the serving binned matrix exactly as the featurizer
    produced it (``[rung, F]`` u8/u16, or ``[rung, ceil(F/2)]`` nibble-
    packed under pack4 — unpacked in-program); ``n_valid`` rides as a
    traced scalar so the program is keyed on the rung alone. A pure
    on-device scatter-add: nothing here reads back to the host."""
    import jax
    import jax.numpy as jnp

    from ..ops.packed import unpack4

    def accum(occ, bins, n_valid):
        full = (unpack4(bins, num_features) if packed
                else bins).astype(jnp.int32)
        idx = full + jnp.arange(num_features,
                                dtype=jnp.int32)[None, :] * bin_width
        mask = (jnp.arange(bins.shape[0]) < n_valid).astype(occ.dtype)
        return occ.at[idx].add(jnp.broadcast_to(mask[:, None], idx.shape))

    return jax.jit(accum)


@functools.lru_cache(maxsize=None)
def _score_accum_fn(num_class: int, score_bins: int):
    """Jitted fixed-edge margin histogram add: ``hist[K, SB] +=
    bincount(clip(floor((raw - lo)/width)))`` over the valid column
    prefix of a ``[K, rung]`` raw-score matrix.

    ``lo``/``width`` ride as TRACED scalars (like ``n_valid``), not
    cache keys: they differ per model version, and keying the jit cache
    on them would retain one compiled program per hot-swapped model
    forever in a long-lived refit loop."""
    import jax
    import jax.numpy as jnp

    def accum(hist, raw, n_valid, lo, width):
        idx = jnp.clip(jnp.floor((raw - lo) / width), 0,
                       score_bins - 1).astype(jnp.int32)
        k = jnp.arange(num_class, dtype=jnp.int32)[:, None] * score_bins
        mask = (jnp.arange(raw.shape[1]) < n_valid).astype(hist.dtype)
        flat = hist.reshape(-1).at[idx + k].add(
            jnp.broadcast_to(mask[None, :], idx.shape))
        return flat.reshape(num_class, score_bins)

    return jax.jit(accum)


def _host_bin_counts(bins: np.ndarray, n: int, num_features: int,
                     bin_width: int) -> np.ndarray:
    """Host twin of the device accumulate (``tpu_serve_featurize=host``)."""
    b = np.asarray(bins[:n], np.int64)
    idx = b + np.arange(num_features, dtype=np.int64)[None, :] * bin_width
    return np.bincount(idx.ravel(), minlength=num_features * bin_width)


def _score_bincount(scores: np.ndarray, lo: float, width: float,
                    score_bins: int) -> np.ndarray:
    """``[K, SB]`` fixed-edge histogram with the device program's exact
    clamp semantics (under/overflow lands in the edge bins)."""
    s = np.asarray(scores, np.float64)
    idx = np.clip(np.floor((s - lo) / width), 0,
                  score_bins - 1).astype(np.int64)
    k = np.arange(idx.shape[0], dtype=np.int64)[:, None] * score_bins
    return np.bincount((idx + k).ravel(),
                       minlength=idx.shape[0] * score_bins
                       ).reshape(idx.shape[0], score_bins)


class DriftMonitor:
    """Per-model drift state: reference distributions, device window
    accumulators, and hysteresis-gated PSI events.

    Built at model attach (server start / hot-swap commit) from the
    booster's ``drift_reference()`` — the training data's bin occupancy
    and raw-margin histogram, which the registry materializes during the
    warm phase so the swap flip never stalls on a data pass."""

    def __init__(self, version: str, booster, *, flush_every: int,
                 psi_threshold: float, score_bins: int,
                 drift_bins: int = 16, min_rows: int = 0,
                 stream_path: str = ""):
        inner = booster._gbdt
        ds = inner.train_set
        probs, nbins, ref_scores = inner.drift_reference()
        self.version = str(version)
        self.flush_every = int(flush_every)
        self.threshold = float(psi_threshold)
        #: hysteresis band: cleared only below HALF the enter threshold
        self.exit_threshold = 0.5 * self.threshold
        self._stream_path = str(stream_path or "")
        self.feature_names = list(ds.feature_names)
        self._ref = np.asarray(probs, np.float64)
        self._nbins = np.asarray(nbins, np.int64)
        self._F, self._B = self._ref.shape
        # PSI compares ~equal-reference-mass GROUPS of adjacent bins
        # (tpu_drift_bins), not the raw mapper bins — see
        # equal_mass_groups for why fine bins would cry wolf
        self._G = max(2, min(int(drift_bins), self._B))
        self._gid = equal_mass_groups(self._ref, self._G)
        rg = group_counts(self._ref, self._gid, self._G)
        self._ref_g = rg / np.maximum(rg.sum(axis=1, keepdims=True), 1e-12)
        # event gate: PSI sampling noise has expectation ~(G-1)/rows, so
        # a window below ~20G rows would fire spurious events on
        # unshifted low-traffic services; gauges/records still update,
        # only the hysteresis TRANSITIONS wait for a big-enough window
        self.min_rows = int(min_rows) if int(min_rows) > 0 \
            else 20 * self._G
        self._packed = bool(getattr(inner, "_pred_pack4", False))
        self._bins_dtype = ds.binned.dtype
        self._K = int(inner.num_tree_per_iteration)
        self._SB = max(int(score_bins), 2)
        self._SG = max(2, min(self._G, self._SB))
        if ref_scores is not None:
            rs = np.asarray(ref_scores, np.float64).reshape(self._K, -1)
            lo, hi = float(rs.min()), float(rs.max())
            pad = 0.05 * (hi - lo) or 0.5
            self._lo, self._hi = lo - pad, hi + pad
            self._width = (self._hi - self._lo) / self._SB
            h = _score_bincount(rs, self._lo, self._width, self._SB)
            self._set_score_ref(h)
        else:
            # no training margins (unusual): the first flushed window
            # becomes the score baseline (that flush reports 0 drift)
            self._score_ref = None
            self._score_gid = None
            self._lo, self._hi = -10.0, 10.0
            self._width = (self._hi - self._lo) / self._SB
        # window accumulators. Device arrays take pure on-device adds in
        # the serve tick; the host twins absorb the
        # tpu_serve_featurize=host escape hatch. Both zero at flush.
        self._occ_dev = None
        self._shist_dev = None
        self._occ_host = np.zeros(self._F * self._B, np.int64)
        self._shist_host = np.zeros((self._K, self._SB), np.int64)
        self.window_rows = 0
        self.score_rows = 0
        self.flushes = 0
        #: device->host syncs — exactly one per flush, nothing per tick
        #: (the steady-state guard tests read this)
        self.host_syncs = 0
        self.events_total = 0
        self._drifted = np.zeros(self._F, bool)
        self._score_drifted = False
        self._last_psi = np.zeros(self._F)
        self._last_kl = np.zeros(self._F)
        self._last_score_psi: Optional[float] = None
        self._gauges: Dict[str, Any] = {}
        self._gmu = threading.Lock()

    def _set_score_ref(self, hist: np.ndarray) -> None:
        """Baseline the score distribution: fixed-edge bin histogram ->
        equal-mass groups (same cry-wolf fix as the feature bins)."""
        p = np.asarray(hist, np.float64)
        p = p / np.maximum(p.sum(axis=1, keepdims=True), 1)
        self._score_gid = equal_mass_groups(p, self._SG)
        g = group_counts(p, self._score_gid, self._SG)
        self._score_ref = g / np.maximum(g.sum(axis=1, keepdims=True),
                                         1e-12)

    # -- accumulate (serving worker thread, inside the serve tick) ----------
    def _reset_device(self):
        import jax.numpy as jnp
        # int32 counts, not f32: a float accumulator silently saturates
        # at 2^24 rows per bin (x + 1 == x), under-counting dominant
        # bins on long flush cadences at high QPS
        self._occ_dev = jnp.zeros(self._F * self._B, jnp.int32)
        self._shist_dev = jnp.zeros((self._K, self._SB), jnp.int32)
        return self._occ_dev

    def observe_binned(self, binned, n: int) -> None:
        """Fold one served batch's binned matrix into the occupancy
        window: a device scatter-add for device-featurized batches, a
        host bincount for the host-binned escape hatch."""
        if isinstance(binned, np.ndarray):
            self._occ_host += _host_bin_counts(binned, int(n), self._F,
                                               self._B)
        else:
            if self._occ_dev is None:
                self._reset_device()
            fn = _bin_accum_fn(self._packed, self._F, self._B)
            self._occ_dev = fn(self._occ_dev, binned, np.int32(n))
        self.window_rows += int(n)

    def observe_scores(self, raw, n: int) -> None:
        """Fold one predict batch's raw margins ``[K, rung]`` into the
        fixed-edge score histogram window."""
        if isinstance(raw, np.ndarray):
            self._shist_host += _score_bincount(
                raw[:, :int(n)], self._lo, self._width, self._SB)
        else:
            if self._shist_dev is None:
                self._reset_device()
            fn = _score_accum_fn(self._K, self._SB)
            self._shist_dev = fn(self._shist_dev, raw, np.int32(n),
                                 np.float32(self._lo),
                                 np.float32(self._width))
        self.score_rows += int(n)

    def warm(self, rungs: Sequence[int]) -> None:
        """Pre-lower the accumulate programs for every warmed serving
        rung (one program per rung, exactly like the predict ladder) and
        the reset constants, so an armed monitor compiles NOTHING in
        steady state; the dummy window is discarded."""
        import jax
        import jax.numpy as jnp

        from ..analysis.guards import compile_phase
        cols = (self._F + 1) // 2 if self._packed else self._F
        with compile_phase("predict_warmup"):
            for rung in rungs:
                self.observe_binned(
                    jnp.zeros((int(rung), cols), self._bins_dtype), 0)
                self.observe_scores(
                    jnp.zeros((self._K, int(rung)), jnp.float32), 0)
            jax.block_until_ready(self._reset_device())
        self._occ_host[:] = 0
        self._shist_host[:] = 0
        self.window_rows = 0
        self.score_rows = 0

    # -- flush (the declared d2h tick) --------------------------------------
    def _psi_keep(self, psi: np.ndarray) -> List[int]:
        """Feature indices embedded in records/gauges: all of them for
        ordinary widths, the top :data:`PSI_MAP_CAP` plus every drifted
        feature for very wide datasets."""
        if self._F <= PSI_MAP_FULL_MAX:
            return list(range(self._F))
        top = np.argsort(psi)[::-1][:PSI_MAP_CAP]
        return sorted(set(top.tolist())
                      | set(np.nonzero(self._drifted)[0].tolist()))

    def flush(self, stream=None) -> Dict[str, Any]:
        """Close the window: ONE device->host sync of the accumulators,
        PSI/KL vs the reference, hysteresis-gated events into the flight
        recorder, gauges for the Prometheus endpoint, and a
        ``drift_flush`` record into the metrics stream (when armed)."""
        occ = np.asarray(self._occ_host, np.float64).copy()
        shist = np.asarray(self._shist_host, np.float64).copy()
        if self._occ_dev is not None:
            self.host_syncs += 1
            occ += np.asarray(self._occ_dev, np.float64)
            shist += np.asarray(self._shist_dev, np.float64)
            self._reset_device()
        self._occ_host[:] = 0
        self._shist_host[:] = 0
        rows, srows = self.window_rows, self.score_rows
        self.window_rows = 0
        self.score_rows = 0
        # NOTE: self.flushes advances at the END of this method — it is
        # the completion signal clients poll (tests, operators), so the
        # events/gauges/records must already be visible when it moves
        flush_no = self.flushes + 1
        occ = occ.reshape(self._F, self._B)
        events: List[Tuple[str, str, float]] = []
        low_traffic = rows < self.min_rows
        if rows > 0:
            occ_g = group_counts(occ, self._gid, self._G)
            cur = occ_g / max(rows, 1)
            psi = psi_rows(self._ref_g, cur)
            klv = kl_rows(self._ref_g, cur)
            # single-bin features cannot drift in bin space
            psi = np.where(self._nbins > 1, psi, 0.0)
            klv = np.where(self._nbins > 1, klv, 0.0)
            if not low_traffic:
                entered = (psi >= self.threshold) & ~self._drifted
                cleared = (psi < self.exit_threshold) & self._drifted
                for j in np.nonzero(entered)[0]:
                    events.append(("drift_detected",
                                   self.feature_names[j], float(psi[j])))
                for j in np.nonzero(cleared)[0]:
                    events.append(("drift_cleared",
                                   self.feature_names[j], float(psi[j])))
                self._drifted |= entered
                self._drifted &= ~cleared
            self._last_psi, self._last_kl = psi, klv
        else:
            psi, klv = self._last_psi, self._last_kl
        score_psi = None
        if srows > 0:
            if self._score_ref is None:
                self._set_score_ref(shist)
            sg = group_counts(shist, self._score_gid, self._SG)
            curs = sg / np.maximum(sg.sum(axis=1, keepdims=True), 1)
            score_psi = float(psi_rows(self._score_ref, curs).max())
            if srows >= self.min_rows:
                if score_psi >= self.threshold \
                        and not self._score_drifted:
                    self._score_drifted = True
                    events.append(("drift_detected", SCORE_FEATURE,
                                   score_psi))
                elif score_psi < self.exit_threshold \
                        and self._score_drifted:
                    self._score_drifted = False
                    events.append(("drift_cleared", SCORE_FEATURE,
                                   score_psi))
            self._last_score_psi = score_psi
        self.events_total += len(events)
        jmax = int(np.argmax(psi)) if self._F else 0
        drifted = [self.feature_names[j]
                   for j in np.nonzero(self._drifted)[0]]
        keep = self._psi_keep(psi)
        record = {
            "version": self.version, "flush": flush_no,
            "window_rows": rows, "score_rows": srows,
            "threshold": self.threshold,
            "psi": {self.feature_names[j]: round(float(psi[j]), 6)
                    for j in keep},
            "kl": {self.feature_names[j]: round(float(klv[j]), 6)
                   for j in keep},
            "max_psi": round(float(psi[jmax]), 6) if self._F else 0.0,
            "max_feature": self.feature_names[jmax] if self._F else None,
            "score_psi": (round(score_psi, 6)
                          if score_psi is not None else None),
            "score_drifted": self._score_drifted,
            "low_traffic": low_traffic,
            "min_rows": self.min_rows,
            "drifted": drifted,
            "events": [{"event": e, "feature": f, "psi": round(p, 6)}
                       for e, f, p in events],
        }
        flight.note("drift_flush", version=self.version,
                    flush=flush_no, window_rows=rows,
                    max_psi=record["max_psi"],
                    max_feature=record["max_feature"],
                    score_psi=record["score_psi"],
                    drifted=len(drifted))
        for e, f, p in events:
            flight.note(e, feature=f, psi=round(p, 6),
                        version=self.version, flush=flush_no)
        if stream is None and self._stream_path:
            stream = obs_metrics.stream_for(self._stream_path)
        if stream is not None:
            stream.emit("drift_flush", **record)
        with self._gmu:
            self._gauges = {
                "psi": record["psi"],
                "score_psi": record["score_psi"],
                "max_psi": record["max_psi"],
                "max_feature": record["max_feature"],
                "drifted": drifted,
                "score_drifted": self._score_drifted,
                "flushes": flush_no,
                "window_rows": rows,
                "events_total": self.events_total,
            }
        self.flushes = flush_no     # LAST: the poll-visible completion
        return record

    def gauges(self) -> Dict[str, Any]:
        with self._gmu:
            return dict(self._gauges)


class LatencyHistogram:
    """Fixed-bucket latency histogram (Prometheus ``le`` semantics)."""

    __slots__ = ("counts", "sum_ms", "count")

    def __init__(self):
        self.counts = [0] * (len(LATENCY_BUCKETS_MS) + 1)  # + overflow
        self.sum_ms = 0.0
        self.count = 0

    def observe(self, ms: float) -> None:
        self.counts[bisect.bisect_left(LATENCY_BUCKETS_MS, ms)] += 1
        self.sum_ms += float(ms)
        self.count += 1

    def snapshot(self) -> Dict[str, Any]:
        return {"counts": list(self.counts), "sum_ms": self.sum_ms,
                "count": self.count}


class SloTracker:
    """Rolling good/bad counts + multi-window error-budget burn rates.

    10 s buckets over a 1 h horizon; ``burn_rate(w)`` is the window's
    bad fraction over the allowed ``1 - target`` budget — 1.0 means
    spending the error budget exactly as fast as the SLO allows."""

    BUCKET_S = 10.0
    HORIZON_S = 3600.0
    WINDOWS_S = (("5m", 300.0), ("1h", 3600.0))

    def __init__(self, slo_ms: float, target: float):
        self.slo_ms = float(slo_ms)
        self.target = min(max(float(target), 0.0), 1.0 - 1e-9)
        self._n = int(self.HORIZON_S / self.BUCKET_S)
        self._good = np.zeros(self._n, np.int64)
        self._bad = np.zeros(self._n, np.int64)
        self._ids = np.full(self._n, -1, np.int64)
        self.good_total = 0
        self.bad_total = 0
        self.alerting = False

    def _slot(self, now: float) -> int:
        bid = int(now / self.BUCKET_S)
        s = bid % self._n
        if self._ids[s] != bid:       # lazily retire the stale horizon
            self._good[s] = 0
            self._bad[s] = 0
            self._ids[s] = bid
        return s

    def record(self, good: bool, now: Optional[float] = None) -> None:
        s = self._slot(time.monotonic() if now is None else now)
        if good:
            self._good[s] += 1
            self.good_total += 1
        else:
            self._bad[s] += 1
            self.bad_total += 1

    def window_counts(self, window_s: float,
                      now: Optional[float] = None) -> Tuple[int, int]:
        now = time.monotonic() if now is None else now
        bid = int(now / self.BUCKET_S)
        k = min(int(math.ceil(window_s / self.BUCKET_S)), self._n)
        ids = np.arange(bid - k + 1, bid + 1, dtype=np.int64)
        slots = ids % self._n
        live = self._ids[slots] == ids
        return (int(self._good[slots][live].sum()),
                int(self._bad[slots][live].sum()))

    def burn_rate(self, window_s: float,
                  now: Optional[float] = None) -> float:
        g, b = self.window_counts(window_s, now)
        t = g + b
        if t == 0:
            return 0.0
        return (b / t) / max(1.0 - self.target, 1e-9)

    def snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        out = {"slo_ms": self.slo_ms, "target": self.target,
               "good_total": self.good_total, "bad_total": self.bad_total,
               "alerting": self.alerting}
        for name, w in self.WINDOWS_S:
            out[f"burn_{name}"] = round(self.burn_rate(w, now), 4)
        return out


class ServingObserver:
    """The serving tier's quality plane, owned by one PredictionServer.

    The coalescer notifies it of every completed/failed future
    (``on_future_done``) and of every served tick (``on_tick_served`` —
    the drift flush cadence); the server passes its active
    :class:`DriftMonitor` (``drift_for``) into the serving prediction
    calls so bins and margins accumulate on device inside the tick.
    Latency histograms and the SLO tracker are always on (host-side
    counters, a few ns per request); drift arms via
    ``tpu_drift_flush_every > 0``, SLO via ``tpu_serve_slo_ms > 0``."""

    def __init__(self, cfg, *, slo_ms=None, slo_target=None,
                 drift_flush_every=None, drift_psi_threshold=None):
        def get(key, default):
            try:
                return cfg.get(key, default)
            except Exception:  # noqa: BLE001 - config-less construction
                return default
        self.flush_every = int(
            drift_flush_every if drift_flush_every is not None
            else get("tpu_drift_flush_every", 0) or 0)
        self.psi_threshold = float(
            drift_psi_threshold if drift_psi_threshold is not None
            else get("tpu_drift_psi_threshold", 0.2) or 0.2)
        self.score_bins = int(get("tpu_drift_score_bins", 32) or 32)
        self.drift_bins = int(get("tpu_drift_bins", 16) or 16)
        self.min_rows = int(get("tpu_drift_min_rows", 0) or 0)
        slo_ms = float(slo_ms if slo_ms is not None
                       else get("tpu_serve_slo_ms", 0.0) or 0.0)
        target = float(slo_target if slo_target is not None
                       else get("tpu_serve_slo_target", 0.99) or 0.99)
        self.slo = SloTracker(slo_ms, target) if slo_ms > 0 else None
        #: burn-rate alert evaluation is throttled to ~1/s: transitions
        #: move at bucket granularity, and the full window scan must not
        #: run per request on the admission/completion hot paths
        self._next_alert_check = 0.0
        self._stream_path = str(get("tpu_metrics_path", "") or "")
        #: slo stream-record cadence when drift flushing is off
        self._slo_emit_every = (self.flush_every
                                if self.flush_every > 0 else 256)
        self._mu = threading.Lock()
        self._hists: Dict[Tuple[str, str], LatencyHistogram] = {}
        self._phases: Dict[str, Dict[str, float]] = {}
        #: recently-attached model versions — histogram series for
        #: versions outside this window are pruned at attach (a refit
        #: loop must not grow /metrics cardinality per swap, forever)
        self._recent_versions: collections.deque = collections.deque(
            maxlen=4)
        self._drift: Optional[DriftMonitor] = None
        self._ticks = 0

    # -- model attach (deploy / rollback / warm) ----------------------------
    def attach_model(self, version: str, booster,
                     rungs: Sequence[int]) -> None:
        """(Re)build the drift monitor for the now-active model — fresh
        reference distributions, fresh window, warmed accumulate
        programs. A hot-swap resets the drift window by design: the
        reference is per model. Latency-histogram series for versions
        long since swapped out are pruned here — unbounded per-version
        time-series cardinality is the classic Prometheus anti-pattern,
        and a continuous-refit server swaps forever."""
        version = str(version)
        with self._mu:
            if version in self._recent_versions:
                self._recent_versions.remove(version)
            self._recent_versions.append(version)
            keep = set(self._recent_versions)
            self._hists = {k: h for k, h in self._hists.items()
                           if k[1] in keep}
        if self.flush_every <= 0:
            return
        mon = DriftMonitor(version, booster,
                           flush_every=self.flush_every,
                           psi_threshold=self.psi_threshold,
                           score_bins=self.score_bins,
                           drift_bins=self.drift_bins,
                           min_rows=self.min_rows,
                           stream_path=self._stream_path)
        mon.warm(rungs or ())
        with self._mu:
            self._drift = mon
        flight.note("drift_attach", version=str(version),
                    features=mon._F, bins=mon._B,
                    score_bins=mon._SB)

    def drift_for(self, version) -> Optional[DriftMonitor]:
        """The active drift monitor iff it matches the tick's pinned
        model version (a swap landing mid-queue must not fold one
        model's bins into another's window)."""
        d = self._drift
        if d is not None and d.version == str(version):
            return d
        return None

    @property
    def drift(self) -> Optional[DriftMonitor]:
        return self._drift

    def on_shed(self, kind: str) -> None:
        """A request shed at the admission edge never becomes a future,
        but it IS a failed request from the client's side — an SLO that
        cannot see sheds reports burn rate 0 during the exact overload
        it exists to page on."""
        if self.slo is None:
            return
        with self._mu:
            self.slo.record(False)
        self._check_slo_alert()

    # -- per-future / per-tick hooks (coalescer worker thread) --------------
    def on_future_done(self, fut) -> None:
        err = fut._error
        ok = err is None
        lat = fut.latency_s
        ph = fut.phase_times()
        with self._mu:
            if ok and lat is not None:
                key = (fut.kind, str(fut.version))
                h = self._hists.get(key)
                if h is None:
                    h = self._hists[key] = LatencyHistogram()
                h.observe(lat * 1e3)
            if ph:
                d = self._phases.get(fut.kind)
                if d is None:
                    d = self._phases[fut.kind] = {
                        "queue_wait_s": 0.0, "serve_s": 0.0,
                        "complete_s": 0.0, "count": 0}
                for k, v in ph.items():
                    d[k] += v
                d["count"] += 1
            if self.slo is not None:
                good = (ok and lat is not None
                        and lat * 1e3 <= self.slo.slo_ms)
                self.slo.record(good)
        if self.slo is not None:
            # alert transitions evaluate on EVERY outcome, not just on
            # served ticks: a total outage (every tick failing, every
            # request shed) produces no on_tick_served calls — exactly
            # when the burn alert must fire
            self._check_slo_alert()

    def on_tick_served(self, kind: str) -> None:
        """One served tick: advance the flush cadence, flush the drift
        window when due (the declared d2h tick), emit SLO records, and
        evaluate burn-rate alert transitions."""
        with self._mu:
            self._ticks += 1
            t = self._ticks
        stream = (obs_metrics.stream_for(self._stream_path)
                  if self._stream_path else None)
        d = self._drift
        flushed = (d is not None and self.flush_every > 0
                   and t % self.flush_every == 0)
        if flushed:
            d.flush(stream)
        if self.slo is not None:
            if stream is not None and (flushed
                                       or t % self._slo_emit_every == 0):
                with self._mu:      # a concurrent shed must not tear
                    #                 the emitted totals vs burn rates
                    snap = self.slo.snapshot()
                stream.emit("slo", **snap)
            self._check_slo_alert(force=True)

    def _check_slo_alert(self, force: bool = False) -> None:
        s = self.slo
        now = time.monotonic()
        with self._mu:      # one transition wins: concurrent sheds
            #                 (client threads) race the worker here
            if not force and now < self._next_alert_check:
                return      # throttle: the window scans must not run
                #             per request on the hot paths
            self._next_alert_check = now + 1.0
            # burn over every exposed window (THE one window constant —
            # the alert gate and the gauges must never diverge)
            burns = {name: s.burn_rate(w, now)
                     for name, w in s.WINDOWS_S}
            short = s.WINDOWS_S[0][0]
            tags = {f"burn_{k}": round(v, 3) for k, v in burns.items()}
            if not s.alerting and all(v > 1.0 for v in burns.values()):
                # multi-window gate: a blip the long window has already
                # absorbed does not page; sustained burn on all does
                s.alerting = True
                flight.note("slo_burn", slo_ms=s.slo_ms,
                            good=s.good_total, bad=s.bad_total, **tags)
            elif s.alerting and burns[short] <= 1.0:
                s.alerting = False
                flight.note("slo_burn_cleared", **tags)

    def final_flush(self) -> None:
        """Flush a pending partial window at server close so short-lived
        servers still leave their last drift numbers behind."""
        d = self._drift
        try:
            if d is not None and (d.window_rows or d.score_rows):
                d.flush(obs_metrics.stream_for(self._stream_path)
                        if self._stream_path else None)
        except Exception as err:  # noqa: BLE001 - telemetry on shutdown
            log.warning(f"[serving] final drift flush failed: {err!r}")

    # -- exposition ----------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """Scalar summary for the nested metrics tree (/healthz JSON and
        the flattened gauges); the labeled per-feature series live in
        :meth:`prometheus_text`."""
        with self._mu:
            ticks = self._ticks
        out: Dict[str, Any] = {"ticks": ticks}
        d = self._drift
        if d is not None:
            g = d.gauges()
            out["drift"] = {
                "flushes": d.flushes, "host_syncs": d.host_syncs,
                "window_rows": d.window_rows,
                "events_total": d.events_total,
                "features_drifted": len(g.get("drifted") or ()),
                "max_psi": g.get("max_psi") or 0.0,
                "score_psi": g.get("score_psi") or 0.0,
                "score_drifted": bool(g.get("score_drifted")),
            }
        if self.slo is not None:
            with self._mu:
                out["slo"] = self.slo.snapshot()
        return out

    def prometheus_text(self) -> str:
        """The labeled series the flat gauge tree cannot carry: latency
        histograms per (kind, version), per-phase seconds per kind, and
        per-feature drift PSI — label values escaped per the Prometheus
        text exposition."""
        lines: List[str] = []
        with self._mu:
            hists = {k: h.snapshot() for k, h in self._hists.items()}
            phases = {k: dict(v) for k, v in self._phases.items()}
        for (kind, version), h in sorted(hists.items()):
            lines += obs_metrics.render_histogram(
                "lgbm_tpu_serve_latency_ms",
                {"kind": kind, "version": version},
                LATENCY_BUCKETS_MS, h["counts"], h["sum_ms"], h["count"])
        phase_series = []
        count_series = []
        for kind, d in sorted(phases.items()):
            for phase in ("queue_wait_s", "serve_s", "complete_s"):
                phase_series.append(({"kind": kind,
                                      "phase": phase[:-2]}, d[phase]))
            count_series.append(({"kind": kind}, d["count"]))
        if phase_series:
            lines += obs_metrics.render_gauges(
                "lgbm_tpu_serve_phase_seconds_total", phase_series)
            lines += obs_metrics.render_gauges(
                "lgbm_tpu_serve_requests_observed_total", count_series)
        d = self._drift
        if d is not None:
            g = d.gauges()
            psi_map = g.get("psi") or {}
            drifted = set(g.get("drifted") or ())
            if psi_map:
                lines += obs_metrics.render_gauges(
                    "lgbm_tpu_drift_psi",
                    [({"feature": f, "version": d.version}, v)
                     for f, v in sorted(psi_map.items())])
                lines += obs_metrics.render_gauges(
                    "lgbm_tpu_drift_detected",
                    [({"feature": f, "version": d.version},
                      1.0 if f in drifted else 0.0)
                     for f in sorted(psi_map)])
            if g.get("score_psi") is not None:
                lines += obs_metrics.render_gauges(
                    "lgbm_tpu_drift_score_psi",
                    [({"version": d.version}, float(g["score_psi"]))])
        if self.slo is not None:
            with self._mu:
                s = self.slo.snapshot()
            for key in ("good_total", "bad_total", "burn_5m", "burn_1h"):
                lines += obs_metrics.render_gauges(
                    f"lgbm_tpu_serve_slo_{key}", [({}, float(s[key]))])
            lines += obs_metrics.render_gauges(
                "lgbm_tpu_serve_slo_alerting",
                [({}, 1.0 if s["alerting"] else 0.0)])
        return "\n".join(lines) + ("\n" if lines else "")
