"""Flight recorder: a bounded ring of structured events, dumped on death.

The r05 bench run died with nothing attributable on disk — the retry loop
had host-side prints, the device had a profiler nobody had armed, and the
post-mortem was archaeology over stderr. This module is the black box
that makes the NEXT failure ship its own post-mortem: production code
records cheap structured events into a bounded in-memory ring
(``tpu_flight_buffer`` entries; a dict append under a lock, no I/O, no
device access), and the ring is dumped as JSONL

* on ``TrainingInterrupted`` / any crash escaping engine.train,
* on a blown model hot-swap (serving/registry.py),
* at every checkpoint tick (so even a SIGKILL leaves the ring as of the
  last durable snapshot).

Events recorded by the shipped hooks: iteration ticks, compile events
(phase-keyed, via analysis/guards), persistent-cache hits/misses,
collective-program byte accounting (analysis/hlo.py, when
LGBM_TPU_COMM_ACCOUNTING=1), fault-injection fires, collective deadline /
transient-retry outcomes, checkpoint writes, serving swaps and worker
restarts, and the serving-quality plane (obs/drift.py): drift_flush
summaries, hysteresis-gated drift_detected / drift_cleared — the
machine-readable refit trigger of ROADMAP 4 — and slo_burn /
slo_burn_cleared transitions.

Dump location, first match wins: explicit ``path=``, the
``LGBM_TPU_FLIGHT_PATH`` env var, ``<dump_dir>/flight_<pid>.jsonl`` when
a dump dir was configured (engine.train points it at
``tpu_checkpoint_dir``), else ``lgbm_tpu_flight_<pid>.jsonl`` in the
working directory. The first line of a dump is a header record
(``event: "flight_dump"``) carrying the reason and ring stats; every
subsequent line is one event, oldest first — ``scripts/obs`` pretty-
prints either.
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

#: default ring capacity when no config has been seen (tpu_flight_buffer)
DEFAULT_CAPACITY = 512

#: directory for the last-resort cwd fallback dump path. Empty = the
#: working directory (production default); the test suite points it at
#: a tmpdir so stray dumps can never pollute a checkout (conftest.py).
_FALLBACK_DIR = ""


def _process_rank() -> Optional[int]:
    """This process's rank when running multi-process, else None."""
    try:
        import jax
        if jax.process_count() > 1:
            return int(jax.process_index())
    except Exception:  # noqa: BLE001 - jax absent/uninitialized: single
        pass
    return None


class FlightRecorder:
    """Thread-safe bounded event ring with JSONL dumps."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._mu = threading.Lock()
        self._capacity = int(capacity)
        self._ring: collections.deque = collections.deque(
            maxlen=max(self._capacity, 1))
        self._seq = 0
        self._dump_dir: Optional[str] = None

    # -- configuration -------------------------------------------------------
    @property
    def capacity(self) -> int:
        return self._capacity

    def configure(self, capacity: Optional[int] = None,
                  dump_dir: Optional[str] = None) -> None:
        """Resize the ring / set the default dump directory. Existing
        events are kept (newest-first retention on shrink). Capacity 0
        disables recording entirely."""
        with self._mu:
            if capacity is not None and int(capacity) != self._capacity:
                self._capacity = int(capacity)
                self._ring = collections.deque(
                    self._ring, maxlen=max(self._capacity, 1))
            if dump_dir:
                self._dump_dir = str(dump_dir)

    # -- recording (hot path) ------------------------------------------------
    def record(self, event: str, **fields: Any) -> None:
        """Append one event. Cheap by contract: a dict build and a locked
        deque append — safe from any thread, including serving workers.
        A zero-capacity recorder drops everything."""
        if self._capacity <= 0:
            return
        with self._mu:
            self._seq += 1
            rec = {"seq": self._seq, "t": round(time.time(), 6),
                   "event": event}
            rec.update(fields)
            self._ring.append(rec)

    def events(self) -> List[Dict[str, Any]]:
        with self._mu:
            return list(self._ring)

    def clear(self) -> None:
        with self._mu:
            self._ring.clear()
            self._seq = 0

    # -- dumping -------------------------------------------------------------
    @staticmethod
    def _rank_suffix() -> str:
        """``_rankN`` on EVERY multihost rank (rank 0 included) — dump
        destinations are often shared (env path identical on every rank,
        checkpoint dir on a shared filesystem, pids colliding across
        containers), ranks must not clobber each other's post-mortems,
        and ``scripts/obs merge`` interleaves the per-rank files back
        into one cross-rank timeline by this tag. Single-host paths
        stay exactly as configured."""
        rank = _process_rank()
        return "" if rank is None else f"_rank{rank}"

    def _resolve_path(self, path: Optional[str]) -> str:
        rank = self._rank_suffix()
        if path:
            return str(path)
        env = os.environ.get("LGBM_TPU_FLIGHT_PATH", "")
        if env:
            if rank:
                root, ext = os.path.splitext(env)
                return f"{root}{rank}{ext}"
            return env
        if self._dump_dir:
            return os.path.join(self._dump_dir,
                                f"flight{rank}_{os.getpid()}.jsonl")
        return os.path.join(
            _FALLBACK_DIR, f"lgbm_tpu_flight{rank}_{os.getpid()}.jsonl")

    def dump(self, reason: str, path: Optional[str] = None,
             extra: Optional[Dict[str, Any]] = None) -> Optional[str]:
        """Write the ring as JSONL; returns the path, or None.

        Best-effort by design: a dump runs on failure paths (crash
        handlers, blown swaps) and must never raise — a post-mortem that
        kills the post-mortem writer helps nobody. A DISABLED recorder
        (capacity 0, the documented ``tpu_flight_buffer=0`` off switch)
        writes nothing at all: "0 disables" must not keep littering
        checkpoint dirs with header-only files at every tick."""
        if self._capacity <= 0:
            return None
        try:
            with self._mu:
                events = list(self._ring)
                seq = self._seq
            out = self._resolve_path(path)
            d = os.path.dirname(out)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(out, "w", encoding="utf-8") as fh:
                header = {"event": "flight_dump", "reason": reason,
                          "t": round(time.time(), 6), "pid": os.getpid(),
                          "rank": _process_rank(),
                          "capacity": self._capacity,
                          "events": len(events),
                          "dropped": max(0, seq - len(events))}
                if extra:
                    header.update(extra)
                fh.write(json.dumps(header, default=str) + "\n")
                for rec in events:
                    fh.write(json.dumps(rec, default=str) + "\n")
            return out
        except Exception:  # noqa: BLE001 - never raise from a post-mortem
            return None


#: the process-wide recorder every shipped hook feeds
_RECORDER = FlightRecorder()


def recorder() -> FlightRecorder:
    return _RECORDER


def note(event: str, **fields: Any) -> None:
    """Record one event into the process recorder (the production hook)."""
    _RECORDER.record(event, **fields)


def configure(capacity: Optional[int] = None,
              dump_dir: Optional[str] = None) -> None:
    _RECORDER.configure(capacity=capacity, dump_dir=dump_dir)


def dump(reason: str, path: Optional[str] = None,
         extra: Optional[Dict[str, Any]] = None) -> Optional[str]:
    return _RECORDER.dump(reason, path=path, extra=extra)


def read_dump(path: str) -> List[Dict[str, Any]]:
    """Parse a dump (header + events). Tolerates a torn tail line — the
    dump may have raced a dying process; everything parseable is kept."""
    from .metrics import read_stream
    return read_stream(path)
