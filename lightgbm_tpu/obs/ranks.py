"""Per-rank runtime attribution: who is slow, who waits at the collective.

The reference's distributed learners account communication per rank by
hand around their socket/MPI ``Allreduce``/``ReduceScatter``
(``src/network/network.cpp``); on a TPU pod the collectives are inside
the compiled step, every rank runs the same program, and a single slow
host (preempted neighbor, thermal throttle, input stall) silently sets
the pace of the whole pod — the collectives make everyone wait for the
slowest arrival. This module makes that visible:

* **Sampled timers** (``tpu_rank_stats_every``): at the sampled
  iterations only, the booster brackets its update with
  ``block_until_ready`` (true step wall, collective wait included) and
  times one *collective arrival probe* — between samples nothing is
  timed, blocked, or published, so the steady-state 0-recompile /
  0-host-transfer guard holds off-sample by construction.
* **The probe**: multi-process ranks time their arrival skew at a
  coordination-service KV barrier (the same ``wait_at_barrier`` plumbing
  ``mesh.sync_barrier`` uses — works on every backend, including the
  2-process CPU dryrun); single-process meshes time a pre-compiled
  scalar ``psum`` over the device mesh instead. Either way the number is
  "how long did this rank wait for its slowest peer", the quantity the
  in-step ``psum``/``psum_scatter`` sites experience.
* **Publish + aggregate**: each rank publishes its per-sample payload
  (step seconds, per-iteration wall, collective wait, a heartbeat
  timestamp) through the coordination-service KV. Rank 0 gathers all
  ranks, computes median / p99 / max-over-ranks, and flags stragglers —
  a rank whose iteration wall exceeds ``tpu_straggler_factor`` x its
  peers' concurrent median (so a global slowdown flags nobody and a
  persistent straggler keeps being flagged; with no peers reporting,
  the rolling self-history median is the fallback base) — into the
  flight recorder and the metrics stream.
  A rank whose payload never arrives within the deadline is reported as
  ``rank_missing`` with its last-heartbeat age.

Flight dumps are rank-tagged (``..._rank<k>.jsonl``, obs/flight.py) and
``scripts/obs merge`` interleaves them into one cross-rank timeline.
"""
from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from . import flight

#: KV key namespace (coordination service); run-scoped below
_KV_PREFIX = "lgbm_tpu_rs"

#: rolling window of cross-rank medians the straggler compare uses
_WINDOW = 32

#: process-wide run counter: every rank constructs its RankStats in the
#: same program order (one per training run), so the counter agrees
#: across the pod and keeps two runs' KV keys from colliding
_run_seq = 0
_run_mu = threading.Lock()


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    if n == 0:
        return 0.0
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def _p99(xs: List[float]) -> float:
    s = sorted(xs)
    if not s:
        return 0.0
    idx = max(0, min(len(s) - 1, int(-(-99 * len(s) // 100)) - 1))
    return s[idx]


def _coordination_client():
    """The jax coordination-service KV client, or None (single process /
    internals moved)."""
    try:
        import jax
        if jax.process_count() <= 1:
            return None
        from jax._src import distributed
        return distributed.global_state.client
    except Exception:  # noqa: BLE001 - attribution is best-effort
        return None


class RankStats:
    """Sampled per-rank step/collective-wait attribution (one per run).

    ``kv``/``rank``/``world`` are injectable for tests; production wiring
    (boosting/gbdt.py ``_setup_train``) lets them default to the live
    jax process topology and coordination client.
    """

    def __init__(self, every: int, straggler_factor: float = 3.0,
                 mesh=None, deadline_s: float = 30.0, stream=None,
                 kv=None, rank: Optional[int] = None,
                 world: Optional[int] = None):
        global _run_seq
        self.every = max(1, int(every))
        self.factor = float(straggler_factor)
        self.deadline_s = float(deadline_s) if deadline_s > 0 else 30.0
        self._stream = stream
        if rank is None or world is None:
            try:
                import jax
                rank = jax.process_index() if rank is None else rank
                world = jax.process_count() if world is None else world
            except Exception:  # noqa: BLE001 - no backend: single rank
                rank, world = rank or 0, world or 1
        self.rank = int(rank)
        self.world = int(world)
        self._kv = kv if kv is not None else (
            _coordination_client() if self.world > 1 else None)
        with _run_mu:
            _run_seq += 1
            self._run = _run_seq
        self._mu = threading.Lock()
        self._last_t: Optional[float] = None
        self._last_iter: Optional[int] = None
        self._medians: deque = deque(maxlen=_WINDOW)
        self._last_seen: Dict[int, float] = {}
        self._latest: Dict[str, Any] = {}
        self.straggler_events = 0
        self._probe_fn = None
        self._probe_arg = None
        if self._kv is None and mesh is not None:
            self._build_probe(mesh)

    # -- collective arrival probe -------------------------------------------
    def _build_probe(self, mesh) -> None:
        """Pre-compile the scalar-psum probe OUTSIDE the steady-state
        region (construction time), so sampled probes lower nothing."""
        try:
            import jax
            import jax.numpy as jnp
            import numpy as np
            from ..parallel.mesh import row_sharding
            ndev = len(mesh.devices.ravel())
            if ndev <= 1:
                return
            arg = jax.device_put(np.ones(ndev, np.float32),
                                 row_sharding(mesh))
            fn = jax.jit(lambda x: jnp.sum(x))
            jax.block_until_ready(fn(arg))      # warm: compile here
            self._probe_fn, self._probe_arg = fn, arg
        except Exception:  # noqa: BLE001 - probe is optional attribution
            self._probe_fn = self._probe_arg = None

    def _barrier_step(self, iteration: int) -> None:
        """Arrive at the sample barrier for ``iteration`` (every rank
        calls this at the same sampled iterations; the KV timeout
        bounds a dead peer)."""
        self._kv.wait_at_barrier(
            f"{_KV_PREFIX}_{self._run}_bar_{iteration}",
            int(self.deadline_s * 1000))

    def _kv_arrival_wait(self, iteration: int) -> float:
        # DECLARED R009 tick site (allowlisted): the sampled
        # collective-wait timer — the KV barrier blocks by nature (no
        # device dispatch to block_until_ready on), and the elapsed wall
        # IS the measurement: how long this rank waited for its slowest
        # peer to arrive, the skew the in-step psum sites experience
        t0 = time.perf_counter()
        try:
            self._barrier_step(iteration)
        except Exception:  # noqa: BLE001 - dead peer: the timeout is the wait
            pass
        return time.perf_counter() - t0

    def _probe_wait(self) -> float:
        import jax
        t0 = time.perf_counter()
        jax.block_until_ready(self._probe_fn(self._probe_arg))
        return time.perf_counter() - t0

    def collective_wait(self, iteration: int) -> float:
        """Timed arrival at the collective, per the module docstring."""
        if self._kv is not None:
            return self._kv_arrival_wait(iteration)
        if self._probe_fn is not None:
            return self._probe_wait()
        return 0.0

    # -- sampling ------------------------------------------------------------
    def due(self, iteration: int) -> bool:
        return iteration > 0 and iteration % self.every == 0

    def sample_step(self, iteration: int, step_s: float) -> None:
        """One sampled tick: publish this rank's numbers; aggregate on
        rank 0. ``step_s`` is the block_until_ready-bracketed update
        wall the caller measured (basic.py, the anchored tick site)."""
        now = time.perf_counter()
        if self._last_t is not None and iteration > (self._last_iter or 0):
            iter_s = (now - self._last_t) / (iteration - self._last_iter)
        else:
            iter_s = step_s
        wait_s = self.collective_wait(iteration)
        payload = {
            "rank": self.rank, "iteration": int(iteration),
            "step_s": round(step_s, 6), "iter_s": round(iter_s, 6),
            "wait_s": round(wait_s, 6),
            # the heartbeat: rank 0 ages it when a later payload never
            # arrives (preempted peer vs merely slow)
            "hb": round(time.time(), 6),
        }
        flight.note("rank_sample", **payload)
        self._publish(payload)
        if self.rank == 0:
            self._aggregate(iteration, payload)
        # re-stamp AFTER the sampling overhead: the barrier wait and the
        # rank-0 KV gather must not leak into the next window's
        # iteration wall — the rank that WAITED for a straggler would
        # otherwise be flagged as the next sample's straggler
        self._last_t, self._last_iter = time.perf_counter(), iteration

    # -- KV plumbing ---------------------------------------------------------
    def _key(self, iteration: int, rank: int) -> str:
        return f"{_KV_PREFIX}/{self._run}/{iteration}/{rank}"

    def _publish(self, payload: Dict[str, Any]) -> None:
        if self._kv is None or self.rank == 0:
            return
        try:
            self._kv.key_value_set(self._key(payload["iteration"],
                                             self.rank),
                                   json.dumps(payload))
        except Exception:  # noqa: BLE001 - attribution must not kill training
            pass

    def _gather(self, iteration: int) -> Dict[int, Dict[str, Any]]:
        out = {}
        if self._kv is None:
            return out
        # ONE shared deadline for the whole gather, not a fresh one per
        # rank: with k preempted ranks a per-rank budget would stall
        # rank 0's sampled update k x deadline_s — long enough to trip
        # the engine's own collective watchdog on a self-inflicted wait
        budget_end = time.perf_counter() + self.deadline_s
        for r in range(1, self.world):
            remaining_ms = int((budget_end - time.perf_counter()) * 1000)
            if remaining_ms <= 0:
                break
            try:
                raw = self._kv.blocking_key_value_get(
                    self._key(iteration, r), remaining_ms)
                out[r] = json.loads(raw)
            except Exception:  # noqa: BLE001 - missing rank reported below
                continue
        return out

    # -- rank-0 aggregation --------------------------------------------------
    def _aggregate(self, iteration: int,
                   own: Dict[str, Any]) -> Dict[str, Any]:
        ranks: Dict[int, Dict[str, Any]] = {0: own}
        ranks.update(self._gather(iteration))
        now = time.time()
        for r, p in ranks.items():
            self._last_seen[r] = float(p.get("hb", now))
        missing = [r for r in range(self.world) if r not in ranks]
        for r in missing:
            age = now - self._last_seen.get(r, now)
            flight.note("rank_missing", rank=r, iteration=iteration,
                        heartbeat_age_s=round(age, 3))
        # the attribution quantity: the slowest of (blocked step wall,
        # per-iteration loop wall) — host-side stalls between updates
        # (input pipeline, a hung callback) pace the pod just as surely
        # as a slow device step
        slow = {r: max(float(p.get("step_s", 0.0)),
                       float(p.get("iter_s", 0.0)))
                for r, p in ranks.items()}
        med = _median(list(slow.values()))
        rolling = _median(list(self._medians) + [med])
        self._medians.append(med)
        # a rank is a straggler when it exceeds the factor x its PEERS'
        # concurrent median — peers, not the pod median, so a global
        # slowdown (shared input stall) flags nobody, and a PERSISTENT
        # straggler keeps getting flagged (a rolling pod median would
        # absorb its inflated samples and go quiet after a few ticks).
        # With no peers reporting (single process, or every other rank
        # missing) the rolling self-history median is the fallback base,
        # so a single-process hang still shows.
        stragglers = []
        for r, s in slow.items():
            others = [v for q, v in slow.items() if q != r]
            base = _median(others) if others else rolling
            if base > 0.0 and s > self.factor * base:
                stragglers.append(r)
        stragglers.sort()
        agg = {
            "iteration": int(iteration),
            "ranks_reporting": len(ranks),
            "world": self.world,
            "median_s": round(med, 6),
            "rolling_median_s": round(rolling, 6),
            "p99_s": round(_p99(list(slow.values())), 6),
            "max_s": round(max(slow.values()), 6),
            "max_rank": max(slow, key=lambda r: slow[r]),
            "wait_median_s": round(_median(
                [float(p.get("wait_s", 0.0)) for p in ranks.values()]), 6),
            "wait_max_s": round(max(
                float(p.get("wait_s", 0.0)) for p in ranks.values()), 6),
            "stragglers": stragglers,
            "missing": missing,
        }
        with self._mu:
            self._latest = dict(agg)
            self._latest["per_rank"] = {str(r): ranks[r] for r in ranks}
        for r in stragglers:
            self.straggler_events += 1
            flight.note("straggler", rank=r, iteration=iteration,
                        slow_s=round(slow[r], 6),
                        rolling_median_s=round(rolling, 6),
                        factor=self.factor)
        if self._stream is not None:
            self._stream.emit("rank_stats", **agg)
        return agg

    # -- consumers -----------------------------------------------------------
    def latest_tree(self) -> Dict[str, Any]:
        """The last aggregate (rank 0) or this rank's config — the
        training MetricsServer's ``rank_stats`` subtree."""
        with self._mu:
            out = dict(self._latest)
        out.setdefault("world", self.world)
        out["rank"] = self.rank
        out["every"] = self.every
        out["straggler_factor"] = self.factor
        out["straggler_events"] = self.straggler_events
        return out
