"""Scaling-efficiency ledger: measured curves, not guesses, in the books.

ROADMAP item 2 wants "honest scaling curves" and "per-chip scaling
efficiency recorded in MULTICHIP/COMM_ACCOUNTING"; this module is the
recorder. Three pure pieces (unit-testable, jax-free) plus one
read-modify-write sink:

* :func:`per_chip_efficiency` — throughput at N chips vs N x the 1-chip
  row: the number a scaling curve is FOR (1.0 = perfect linear, the
  reference's docs/Experiments.rst parallel-learning tables report the
  same shape).
* :func:`measured_vs_model` — the measured collective seconds from the
  device-time trace analytics (obs/tracing.py) against the byte model
  the HLO contracts already pin (``analysis/contracts/*.json``
  ``measured.total`` bytes/step): comm fraction of device busy time,
  modeled bytes moved, and the bandwidth the two numbers jointly imply.
  A wild implied bandwidth means one of the two books is lying — which
  is the point of keeping both.
* :func:`ledger_block` — one bench round's ledger entry: the efficiency
  row, the measured-vs-model block, and enough context (shape, chips,
  throughput) to re-derive either.
* :func:`record` — merge a block into a ledger JSON file
  (COMM_ACCOUNTING.json / MULTICHIP_r0x.json) atomically
  (write-temp-rename; bench rounds may be killed mid-write).

bench.py arms this with ``BENCH_LEDGER=1``: the timed loop runs under a
profiler ``trace_session``, the trace analytics produce the measured
collective durations, and the round's ``measured_vs_model`` block lands
in the books with attribution built in, not bolted on.
"""
from __future__ import annotations

import json
import os
import tempfile
from typing import Any, Dict, List, Optional

#: ledger schema version (consumers key on it before trusting fields)
LEDGER_VERSION = 1


def per_chip_efficiency(rows: List[Dict[str, Any]]
                        ) -> List[Dict[str, Any]]:
    """Scaling efficiency per row vs the 1-chip row.

    ``rows``: ``[{"n_chips": int, "iters_per_sec": float}, ...]`` where
    ``iters_per_sec`` is the WHOLE-RUN throughput (not per-chip).
    Returns the rows augmented with ``per_chip`` and ``efficiency``
    (None when no 1-chip row exists to normalize against).
    """
    base = None
    for r in rows:
        if int(r.get("n_chips", 0)) == 1:
            base = float(r["iters_per_sec"])
            break
    out = []
    for r in rows:
        n = max(1, int(r.get("n_chips", 1)))
        ips = float(r.get("iters_per_sec", 0.0))
        row = dict(r)
        row["per_chip"] = round(ips / n, 6)
        row["efficiency"] = (round(ips / (n * base), 4)
                             if base else None)
        out.append(row)
    return out


def comm_fraction(analysis: Dict[str, Any]) -> Optional[float]:
    """Measured comm share of device busy time from a trace analysis
    (obs/tracing.py output); None when nothing was busy."""
    d = analysis.get("decomposition") or {}
    busy = float(d.get("busy_seconds", 0.0) or 0.0)
    if busy <= 0.0:
        return None
    return round(float(d.get("comm_seconds", 0.0) or 0.0) / busy, 6)


def model_bytes_per_step(contract: Dict[str, Any]) -> Optional[int]:
    """The byte model a checked-in HLO contract pins for one step
    (``measured.total`` — the bytes the lowered collectives move)."""
    measured = contract.get("measured") or {}
    total = measured.get("total")
    return None if total is None else int(total)


def measured_vs_model(analysis: Dict[str, Any],
                      contract: Optional[Dict[str, Any]],
                      steps: Optional[int] = None) -> Dict[str, Any]:
    """One comparison block: measured collective seconds (trace) vs the
    contract's byte model, and the bandwidth they jointly imply."""
    d = analysis.get("decomposition") or {}
    comm_s = float(d.get("comm_seconds", 0.0) or 0.0)
    block: Dict[str, Any] = {
        "measured": {
            "comm_seconds": round(comm_s, 9),
            "busy_seconds": round(
                float(d.get("busy_seconds", 0.0) or 0.0), 9),
            "comm_fraction": comm_fraction(analysis),
            "collectives": analysis.get("collectives", {}),
            "source": analysis.get("source"),
        },
        "model": {},
    }
    bps = model_bytes_per_step(contract) if contract else None
    if bps is not None:
        block["model"] = {
            "bytes_per_step": bps,
            "mode": contract.get("mode"),
            "num_devices": contract.get("num_devices"),
        }
        if steps:
            total_bytes = bps * int(steps)
            block["model"]["bytes_total"] = total_bytes
            if comm_s > 0.0:
                block["implied_gbps"] = round(
                    total_bytes / comm_s / 1e9, 6)
    return block


def load_contract(mode: str) -> Optional[Dict[str, Any]]:
    """A checked-in HLO contract by mode name (``data_scatter``,
    ``serial_compact``, ...), or None."""
    d = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "analysis", "contracts")
    path = os.path.join(d, f"{mode}.json")
    if not os.path.exists(path):
        return None
    try:
        with open(path, encoding="utf-8") as fh:
            return json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None


def ledger_block(shape: str, n_chips: int, iters_per_sec: float,
                 analysis: Optional[Dict[str, Any]] = None,
                 contract: Optional[Dict[str, Any]] = None,
                 steps: Optional[int] = None,
                 prior_rows: Optional[List[Dict[str, Any]]] = None
                 ) -> Dict[str, Any]:
    """One bench round's ledger entry. ``prior_rows`` (earlier rounds'
    ``{n_chips, iters_per_sec}``) feed the efficiency normalization so a
    multichip round records its efficiency against the recorded 1-chip
    row."""
    rows = list(prior_rows or [])
    rows = [r for r in rows if int(r.get("n_chips", 0)) != int(n_chips)]
    rows.append({"n_chips": int(n_chips),
                 "iters_per_sec": float(iters_per_sec)})
    rows.sort(key=lambda r: int(r["n_chips"]))
    block: Dict[str, Any] = {
        "version": LEDGER_VERSION,
        "shape": shape,
        "n_chips": int(n_chips),
        "iters_per_sec": float(iters_per_sec),
        "scaling": per_chip_efficiency(rows),
    }
    if analysis is not None:
        block["measured_vs_model"] = measured_vs_model(
            analysis, contract, steps=steps)
    return block


def prior_rows(path: str, shape: str) -> List[Dict[str, Any]]:
    """Earlier recorded ``{n_chips, iters_per_sec}`` rows for ``shape``
    from a ledger file — the normalization base a new round's
    efficiency is computed against."""
    if not os.path.exists(path):
        return []
    try:
        with open(path, encoding="utf-8") as fh:
            data = json.load(fh)
    except (OSError, json.JSONDecodeError):
        return []
    out = []
    for blk in (data.get("scaling_ledger") or {}).values():
        if isinstance(blk, dict) and blk.get("shape") == shape \
                and "n_chips" in blk and "iters_per_sec" in blk:
            out.append({"n_chips": int(blk["n_chips"]),
                        "iters_per_sec": float(blk["iters_per_sec"])})
    return out


def record(path: str, key: str, block: Dict[str, Any]) -> None:
    """Merge ``block`` under ``ledger[key]`` of the JSON file at
    ``path`` (created if absent), atomically. Existing unrelated keys
    are preserved — COMM_ACCOUNTING.json carries the byte-model entries
    next to this ledger."""
    data: Dict[str, Any] = {}
    if os.path.exists(path):
        try:
            with open(path, encoding="utf-8") as fh:
                data = json.load(fh)
        except (OSError, json.JSONDecodeError):
            data = {}
    if not isinstance(data, dict):
        data = {}
    ledger = data.setdefault("scaling_ledger", {})
    if not isinstance(ledger, dict):
        ledger = data["scaling_ledger"] = {}
    ledger[key] = block
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as fh:
            json.dump(data, fh, indent=1, sort_keys=True)
            fh.write("\n")
        os.replace(tmp, path)
    except BaseException:
        # BaseException, not OSError: a serializer TypeError or a
        # SimulatedKill mid-dump must not orphan the temp file (R012)
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
