"""lightgbm_tpu.obs — unified telemetry: spans, flight recorder, metrics.

One observability subsystem spanning training, collectives, and serving
(the reproduction's answer to the reference's ``USE_TIMETAG``
``Common::Timer`` registry plus the ops tooling it never had):

* :mod:`.spans` — phase-named spans (``span("hist_build")``): zero-cost
  when disabled, ``jax.named_scope`` under trace so DEVICE programs carry
  the phase names into the ``tpu_trace_dir`` Perfetto/TensorBoard trace,
  host timing + ``TraceAnnotation`` at the declared tick sites;
  ``trace_session`` owns the ``tpu_trace_dir``/``tpu_trace_mode`` knobs.
* :mod:`.flight` — bounded ring of structured events (iteration ticks,
  phase-keyed compile events, collective byte accounting, fault fires,
  deadline/retry outcomes), dumped as JSONL on ``TrainingInterrupted``,
  on a blown hot-swap, and at checkpoint ticks (``tpu_flight_buffer``).
* :mod:`.metrics` — per-iteration JSONL stream (``tpu_metrics_path``;
  bench.py derives its BENCH-row counters from it) and a pull-based
  Prometheus-text endpoint served from PredictionServer
  (``--metrics-port`` on ``scripts/serve``). stdlib HTTP, no new deps.
* :mod:`.summarize` — ``scripts/obs``: per-phase time share + compile /
  collective totals from any of the above artifacts (the
  ``Common::Timer::Print`` analogue), jax-free; subcommands ``trace``
  (device-time table from a profiler artifact) and ``merge``
  (cross-rank flight-dump timeline).
* :mod:`.tracing` — device-time trace analytics: parses the
  ``tpu_trace_dir`` xplane artifact (jax-free protobuf wire reader) and
  maps timed device events back to the span taxonomy — the per-phase
  DEVICE-seconds table, per-collective durations, MXU/comm/idle
  decomposition. Post-run only; tpulint R009c keeps it out of
  jit-reachable modules.
* :mod:`.ranks` — per-rank runtime attribution: sampled step /
  collective-wait timers published over the coordination-service KV,
  rank-0 median/p99/max aggregation + straggler flags
  (``tpu_rank_stats_every`` / ``tpu_straggler_factor``).
* :mod:`.ledger` — scaling-efficiency ledger: per-chip throughput
  efficiency vs the 1-chip row + measured-vs-modeled comm accounting
  recorded into MULTICHIP/COMM_ACCOUNTING.json (bench BENCH_LEDGER=1).
* :mod:`.drift` — serving-quality observability (ROADMAP 4's "observe"
  pillar): on-device per-feature bin-occupancy + raw-margin drift
  monitors flushed on a cadence (``tpu_drift_flush_every``) with
  hysteresis-gated PSI ``drift_detected`` events, per-request latency
  attribution histograms, and the multi-window SLO burn-rate tracker
  (``tpu_serve_slo_ms`` / ``tpu_serve_slo_target``). Module level is
  numpy-only; jax loads lazily inside the device accumulate builders.

This ``__init__`` stays jax-free too (``spans`` and ``ranks`` are the
only jax-touching modules and are imported lazily), so ``scripts/obs``
runs without a backend.
"""
from __future__ import annotations

from . import drift, flight, ledger, metrics, summarize, tracing  # noqa: F401

__all__ = ["drift", "flight", "ledger", "metrics", "summarize", "tracing",
           "spans", "ranks", "configure"]


def __getattr__(name):
    # lazy: spans/ranks import jax; offline consumers (scripts/obs)
    # never pay. importlib (not `from . import`) — the from-form probes
    # this very __getattr__ before importing, which recurses
    if name in ("spans", "ranks"):
        import importlib
        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(name)


def configure(config) -> "metrics.MetricsStream | None":
    """Arm the process-wide telemetry from a resolved config: flight-ring
    capacity (``tpu_flight_buffer``), default dump dir
    (``tpu_checkpoint_dir``), the global phase-keyed compile listener,
    and the ``tpu_metrics_path`` stream (returned; None when unset).

    Called from ``GBDT.__init__`` — one call per booster, idempotent."""
    cap = config.get("tpu_flight_buffer", None)
    dump_dir = str(config.get("tpu_checkpoint_dir", "") or "") or None
    flight.configure(capacity=None if cap is None else int(cap),
                     dump_dir=dump_dir)
    from ..analysis import guards
    guards.install_global_compile_listener()
    # multihost: tpu_metrics_path is typically a shared filesystem (the
    # same deployment contract as tpu_checkpoint_dir, where only process
    # 0 writes) — every rank opening the one stream would truncate and
    # interleave it. Rank 0 writes; the others run streamless.
    import jax
    if jax.process_count() > 1 and jax.process_index() != 0:
        return None
    return metrics.stream_for(config.get("tpu_metrics_path", ""))
