"""Summarize flight-recorder / metrics JSONL: the Common::Timer::Print.

The reference prints a per-phase wall-time table at process exit when
built with ``USE_TIMETAG`` (``Common::Timer::Print``,
include/LightGBM/utils/log.h). Here the equivalent table is derived
offline from the observability artifacts a run leaves behind — a
``tpu_metrics_path`` stream, a flight-recorder dump, or both:

    scripts/obs run_metrics.jsonl flight_1234.jsonl
    scripts/obs --json run_metrics.jsonl
    scripts/obs drift serve_metrics.jsonl     # serving-quality view:
                                              # latest PSI flush + SLO
                                              # burn tail (obs/drift.py)

prints per-phase host time share, phase-keyed compile totals, persistent-
cache hit/miss, collective-program byte totals (when the run captured
them via LGBM_TPU_COMM_ACCOUNTING), iteration throughput, and the tail
of notable events (faults, deadlines, restarts, swaps) — the post-mortem
read of a dead run, or the profile read of a healthy one.

This module is intentionally jax-free (plain json/os), so ``scripts/obs``
runs anywhere in milliseconds, including hosts without a backend.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Sequence

#: event kinds surfaced in the "notable events" tail
NOTABLE = ("fault_fire", "deadline", "retry", "crash",
           "training_interrupted", "swap_failed", "worker_restart",
           "snapshot_corrupt", "straggler", "rank_missing",
           "drift_detected", "drift_cleared", "slo_burn",
           "slo_burn_cleared")


def _read_jsonl(path: str) -> List[Dict[str, Any]]:
    from .metrics import read_stream
    return read_stream(path)


def _kind(rec: Dict[str, Any]) -> str:
    # flight records type themselves with "event" and may carry a
    # PAYLOAD field named "kind" (fault_fire's fault kind); metrics
    # records type with "kind" and never have "event" — so "event"
    # must win the classification
    return str(rec.get("event") or rec.get("kind") or "")


def summarize(paths: Sequence[str]) -> Dict[str, Any]:
    """Aggregate one or more JSONL artifacts into a summary dict."""
    records: List[Dict[str, Any]] = []
    for p in paths:
        records.extend(_read_jsonl(p))

    phase_times: Dict[str, Dict[str, float]] = {}
    compiles: Optional[Dict[str, Any]] = None
    cache: Optional[Dict[str, Any]] = None
    collectives: Dict[str, Dict[str, Any]] = {}
    iters = 0
    iter_seconds = 0.0
    notable: List[Dict[str, Any]] = []
    spans_seen: List[str] = []
    dump_header: Optional[Dict[str, Any]] = None
    device_time: Optional[Dict[str, Any]] = None
    rank_stats: Optional[Dict[str, Any]] = None
    stragglers: List[Dict[str, Any]] = []

    for rec in records:
        k = _kind(rec)
        if k == "iteration":
            iters += 1
            iter_seconds += float(rec.get("seconds", 0.0) or 0.0)
            if isinstance(rec.get("compiles"), dict):
                compiles = rec["compiles"]     # cumulative: keep the last
            if isinstance(rec.get("cache"), dict):
                cache = rec["cache"]
        elif k in ("summary", "mark"):
            if isinstance(rec.get("phase_times"), dict):
                phase_times = rec["phase_times"]
            if isinstance(rec.get("compiles"), dict):
                compiles = rec["compiles"]
            if isinstance(rec.get("cache"), dict):
                cache = rec["cache"]
            if isinstance(rec.get("spans_seen"), list):
                spans_seen = sorted(set(spans_seen)
                                    | set(rec["spans_seen"]))
        elif k == "collective_program":
            collectives[str(rec.get("key"))] = {
                "bytes": rec.get("bytes"), "total": rec.get("total")}
        elif k == "device_time":
            device_time = rec                  # one per run: keep the last
            if isinstance(rec.get("host_phase_times"), dict) \
                    and not phase_times:
                phase_times = rec["host_phase_times"]
        elif k == "rank_stats":
            rank_stats = rec                   # cumulative-ish: keep last
        elif k == "straggler":
            stragglers.append(rec)
        elif k == "flight_dump":
            dump_header = rec
        if k in NOTABLE:
            notable.append(rec)

    total_phase_s = sum(float(v.get("seconds", 0.0) or 0.0)
                        for v in phase_times.values()) or None
    return {
        "records": len(records),
        "iterations": iters,
        "iter_seconds_mean": (iter_seconds / iters) if iters else None,
        "phase_times": phase_times,
        "phase_total_seconds": total_phase_s,
        "device_time": device_time,
        "rank_stats": rank_stats,
        "stragglers": stragglers[-20:],
        "compiles": compiles,
        "cache": cache,
        "collectives": collectives,
        "collective_bytes_total": sum(
            int(v.get("total") or 0) for v in collectives.values()) or None,
        "spans_seen": spans_seen,
        "notable": notable[-20:],
        "dump": dump_header,
    }


def _mark_index(records: List[Dict[str, Any]]) -> Dict[str, Dict[str, Any]]:
    """Last occurrence of each named ``mark`` record."""
    out: Dict[str, Dict[str, Any]] = {}
    for rec in records:
        if _kind(rec) == "mark" and rec.get("name"):
            out[str(rec["name"])] = rec
    return out


def _diff_compiles(a: Optional[Dict], b: Optional[Dict]) -> Dict[str, Any]:
    """b - a of two cumulative compile snapshots (phase-keyed)."""
    a, b = a or {}, b or {}

    def n(d, key):
        return int((d or {}).get(key, 0) or 0)

    phases = set((a.get("by_phase") or {})) | set((b.get("by_phase") or {}))
    by_phase = {}
    for p in sorted(phases):
        pa = (a.get("by_phase") or {}).get(p) or {}
        pb = (b.get("by_phase") or {}).get(p) or {}
        d = {"lowerings": n(pb, "lowerings") - n(pa, "lowerings"),
             "backend_compiles": (n(pb, "backend_compiles")
                                  - n(pa, "backend_compiles"))}
        if d["lowerings"] or d["backend_compiles"]:
            by_phase[p] = d
    return {"lowerings": n(b, "lowerings") - n(a, "lowerings"),
            "backend_compiles": (n(b, "backend_compiles")
                                 - n(a, "backend_compiles")),
            "by_phase": by_phase}


def bench_counters(path: str) -> Optional[Dict[str, Any]]:
    """Derive the BENCH-row counters from a metrics stream.

    Expects the bench marks ``warmup_start``/``warmup_end``/
    ``steady_end`` (each carrying a cumulative ``compiles``/``cache``
    snapshot). Returns None when the stream is missing or unmarked, so
    bench.py can fall back to its inline counters instead of recording a
    half-empty row."""
    if not path or not os.path.exists(path):
        return None
    records = _read_jsonl(path)
    marks = _mark_index(records)
    if not all(m in marks for m in ("warmup_start", "warmup_end",
                                    "steady_end")):
        return None
    w0, w1, s1 = (marks["warmup_start"], marks["warmup_end"],
                  marks["steady_end"])
    warm = _diff_compiles(w0.get("compiles"), w1.get("compiles"))
    steady = _diff_compiles(w1.get("compiles"), s1.get("compiles"))

    def cache_of(rec):
        c = rec.get("cache") or {}
        return {k: int(c.get(k, 0) or 0) for k in ("requests", "hits")}

    # cache counters over the WARMUP window, matching compile_events and
    # the inline warm_cache fallback — mixing windows would let a
    # steady-state compile skew the warm-round hits==requests comparison
    c0, c1 = cache_of(w0), cache_of(w1)
    requests = c1["requests"] - c0["requests"]
    hits = c1["hits"] - c0["hits"]
    return {
        "warmup_seconds": round(float(w1["t"]) - float(w0["t"]), 1),
        "compile_events": warm["lowerings"],
        "compile_events_by_phase": warm["by_phase"],
        "compile_events_steady": steady["lowerings"],
        "compile_cache": {"requests": requests, "hits": hits,
                          "misses": requests - hits},
    }


def _fmt_table(summary: Dict[str, Any]) -> str:
    lines: List[str] = []
    pt = summary["phase_times"]
    total = summary["phase_total_seconds"]
    dt = summary.get("device_time") or {}
    dev_phases = dt.get("phases") or {}
    lines.append(f"records: {summary['records']}  "
                 f"iterations: {summary['iterations']}"
                 + (f"  mean iter: {summary['iter_seconds_mean']:.4f}s"
                    if summary["iter_seconds_mean"] else ""))
    if pt or dev_phases:
        # host and device seconds SIDE BY SIDE: the host column is wall
        # clock at the tick sites (dispatch included), the device column
        # is profiler-measured op time — a large host/device gap on the
        # same phase is dispatch skew, not compute
        lines.append("")
        lines.append(f"{'phase':<20} {'host_s':>10} {'share':>7} "
                     f"{'count':>8} {'device_s':>10}")
        names = set(pt) | set(dev_phases)
        for name in sorted(names, key=lambda n: -max(
                float((pt.get(n) or {}).get("seconds", 0) or 0),
                float((dev_phases.get(n) or {}).get(
                    "device_seconds", 0) or 0))):
            v = pt.get(name) or {}
            s = float(v.get("seconds", 0.0) or 0.0)
            share = (s / total) if total else 0.0
            host = f"{s:>10.3f}" if name in pt else f"{'-':>10}"
            d = dev_phases.get(name) or {}
            dev = (f"{float(d.get('device_seconds', 0.0)):>10.4f}"
                   if name in dev_phases else f"{'-':>10}")
            lines.append(f"{name:<20} {host} {share:>6.1%} "
                         f"{int(v.get('count', 0) or 0):>8} {dev}")
    if dt:
        d = dt.get("decomposition") or {}
        lines.append("")
        lines.append(
            f"device timeline ({dt.get('source')}): "
            f"busy {d.get('busy_seconds', 0):.4f}s = "
            f"mxu {d.get('mxu_seconds', 0):.4f}s + "
            f"comm {d.get('comm_seconds', 0):.4f}s + other; "
            f"idle {d.get('idle_seconds', 0):.4f}s")
        for key, v in sorted((dt.get("collectives") or {}).items()):
            lines.append(f"  collective {key:<22} "
                         f"{v.get('seconds', 0):.6f}s x{v.get('count')}")
    rs = summary.get("rank_stats")
    if rs:
        lines.append("")
        lines.append(
            f"ranks: {rs.get('ranks_reporting')}/{rs.get('world')} "
            f"reporting  median {rs.get('median_s')}s  "
            f"p99 {rs.get('p99_s')}s  max {rs.get('max_s')}s "
            f"(rank {rs.get('max_rank')})  wait_max "
            f"{rs.get('wait_max_s')}s")
        if summary.get("stragglers"):
            for rec in summary["stragglers"][-5:]:
                lines.append(
                    f"  straggler: rank {rec.get('rank')} @ iteration "
                    f"{rec.get('iteration')} ({rec.get('slow_s')}s vs "
                    f"median {rec.get('rolling_median_s')}s)")
    comp = summary["compiles"]
    if comp:
        lines.append("")
        lines.append(f"compiles: {comp.get('lowerings', 0)} lowerings, "
                     f"{comp.get('backend_compiles', 0)} backend")
        for p, d in sorted((comp.get("by_phase") or {}).items()):
            lines.append(f"  {p:<18} {d.get('lowerings', 0):>4} lowerings "
                         f"{d.get('backend_compiles', 0):>4} backend")
    cache = summary["cache"]
    if cache:
        lines.append(f"compile cache: {cache.get('hits', 0)}/"
                     f"{cache.get('requests', 0)} hits")
    if summary["collectives"]:
        lines.append("")
        lines.append(f"collective programs "
                     f"({summary['collective_bytes_total']} bytes/step "
                     f"total):")
        for key, v in sorted(summary["collectives"].items()):
            lines.append(f"  {key:<24} {v.get('total')} bytes "
                         f"{json.dumps(v.get('bytes'), default=str)}")
    if summary["spans_seen"]:
        lines.append("")
        lines.append("spans seen: " + ", ".join(summary["spans_seen"]))
    if summary["dump"]:
        d = summary["dump"]
        lines.append("")
        lines.append(f"flight dump: reason={d.get('reason')!r} "
                     f"events={d.get('events')} dropped={d.get('dropped')}")
    if summary["notable"]:
        lines.append("")
        lines.append("notable events (tail):")
        for rec in summary["notable"]:
            k = _kind(rec)
            # drop only the field that typed the record: a flight
            # event's PAYLOAD "kind" (fault_fire's kill/hang) stays
            rest = {key: v for key, v in rec.items()
                    if key not in ("event", "t", "seq")
                    and not (key == "kind" and rec.get("event") is None)}
            lines.append(f"  {k}: {json.dumps(rest, default=str)}")
    return "\n".join(lines)


def _rank_of_dump(path: str, header: Optional[Dict[str, Any]]) -> int:
    """Rank of a flight dump: the header's rank field, else the
    ``_rank<k>`` filename tag, else 0."""
    if header is not None and header.get("rank") is not None:
        try:
            return int(header["rank"])
        except (TypeError, ValueError):
            pass
    import re
    m = re.search(r"_rank(\d+)", os.path.basename(path))
    return int(m.group(1)) if m else 0


def merge_ranks(paths: Sequence[str]) -> List[Dict[str, Any]]:
    """Interleave rank-tagged flight dumps into ONE cross-rank timeline
    ordered by ``(time, source rank)`` — each record annotated with
    ``src_rank``, the rank whose dump it came from. A separate key on
    purpose: events like ``straggler``/``rank_missing`` carry a payload
    ``rank`` (the rank they are ABOUT), which the annotation must not
    clobber — rank 0's dump says rank 1 straggled. The post-mortem read
    of a pod: rank 1's fault fire lines up against rank 0's straggler
    flag and collective-deadline events in wall-clock order."""
    merged: List[Dict[str, Any]] = []
    for path in paths:
        records = _read_jsonl(path)
        header = records[0] if records \
            and _kind(records[0]) == "flight_dump" else None
        rank = _rank_of_dump(path, header)
        for rec in records:
            out = dict(rec)
            out["src_rank"] = rank
            merged.append(out)
    merged.sort(key=lambda r: (float(r.get("t", 0.0) or 0.0),
                               int(r.get("src_rank", 0)),
                               int(r.get("seq", 0) or 0)))
    return merged


def _fmt_merge(merged: List[Dict[str, Any]]) -> str:
    lines = []
    for rec in merged:
        k = _kind(rec)
        rest = {key: v for key, v in rec.items()
                if key not in ("kind", "event", "t", "seq", "src_rank")}
        lines.append(f"{float(rec.get('t', 0.0) or 0.0):>17.6f} "
                     f"r{rec.get('src_rank', 0)} {k:<22} "
                     f"{json.dumps(rest, default=str)}")
    return "\n".join(lines)


def merge_main(argv: Sequence[str]) -> int:
    ap = argparse.ArgumentParser(
        prog="obs merge",
        description="interleave rank-tagged flight dumps into one "
                    "cross-rank timeline ordered by (time, rank)")
    ap.add_argument("paths", nargs="+", help="rank-tagged dump files")
    ap.add_argument("--jsonl", action="store_true",
                    help="emit merged records as JSONL instead of a table")
    args = ap.parse_args(argv)
    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        print(f"obs merge: no such file: {', '.join(missing)}",
              file=sys.stderr)
        return 2
    merged = merge_ranks(args.paths)
    if args.jsonl:
        for rec in merged:
            print(json.dumps(rec, default=str))
    else:
        print(_fmt_merge(merged))
    return 0


def _fmt_trace(analysis: Dict[str, Any]) -> str:
    """The per-phase device-time table of one profiler artifact."""
    lines = [f"trace: {analysis.get('trace_dir', '')} "
             f"({', '.join(analysis.get('files', []))}) "
             f"source={analysis.get('source')} "
             f"lanes={analysis.get('lanes')}"]
    phases = analysis.get("phases") or {}
    if phases:
        lines.append("")
        lines.append(f"{'phase':<20} {'device_s':>12} {'events':>8}")
        for name, v in sorted(phases.items(),
                              key=lambda kv: -float(
                                  kv[1].get("device_seconds", 0) or 0)):
            lines.append(f"{name:<20} "
                         f"{float(v.get('device_seconds', 0)):>12.6f} "
                         f"{int(v.get('events', 0)):>8}")
    un = float(analysis.get("unattributed_seconds", 0.0) or 0.0)
    if un:
        lines.append(f"{'(unattributed)':<20} {un:>12.6f}")
    d = analysis.get("decomposition") or {}
    lines.append("")
    lines.append(f"timeline: total {d.get('total_seconds', 0):.6f}s  "
                 f"busy {d.get('busy_seconds', 0):.6f}s  "
                 f"mxu {d.get('mxu_seconds', 0):.6f}s  "
                 f"comm {d.get('comm_seconds', 0):.6f}s  "
                 f"idle {d.get('idle_seconds', 0):.6f}s")
    for key, v in sorted((analysis.get("collectives") or {}).items()):
        lines.append(f"  collective {key:<22} "
                     f"{v.get('seconds', 0):.6f}s x{v.get('count')}")
    if analysis.get("spans_lowered"):
        lines.append("")
        lines.append("spans lowered: "
                     + ", ".join(analysis["spans_lowered"]))
    return "\n".join(lines)


def drift_summary(paths: Sequence[str], top: int = 10) -> Dict[str, Any]:
    """Aggregate serving-quality records (``drift_flush`` / ``slo`` plus
    drift/SLO events) from metrics streams / flight dumps into one
    summary dict — the latest flush's PSI table, top-k drifted features,
    score drift, and the SLO burn-rate tail."""
    records: List[Dict[str, Any]] = []
    for p in paths:
        records.extend(_read_jsonl(p))
    # the same flush appears TWICE when given both the metrics stream
    # and a flight dump (the ring carries a summary twin of every
    # drift_flush): dedup by (version, flush), preferring the record
    # with the full psi map (the stream one) over the compact twin
    seen: Dict[tuple, Dict[str, Any]] = {}
    order: List[tuple] = []
    for rec in records:
        if _kind(rec) != "drift_flush":
            continue
        key = (rec.get("version"), rec.get("flush"))
        cur = seen.get(key)
        if cur is None:
            seen[key] = rec
            order.append(key)
        elif isinstance(rec.get("psi"), dict) \
                and not isinstance(cur.get("psi"), dict):
            seen[key] = rec
    flushes = [seen[k] for k in order]
    slo = [r for r in records if _kind(r) == "slo"]
    events = [r for r in records
              if _kind(r) in ("drift_detected", "drift_cleared",
                              "slo_burn", "slo_burn_cleared")]
    latest = flushes[-1] if flushes else None
    table: List[Dict[str, Any]] = []
    for rec in reversed(flushes):
        psi = rec.get("psi")
        if isinstance(psi, dict) and psi:
            klm = rec.get("kl") if isinstance(rec.get("kl"), dict) else {}
            drifted = set(rec.get("drifted") or ())
            table = [{"feature": k, "psi": float(v),
                      "kl": klm.get(k), "drifted": k in drifted}
                     for k, v in sorted(psi.items(),
                                        key=lambda kv: -float(kv[1]))]
            break
    return {
        "flushes": len(flushes),
        "latest": latest,
        "psi_table": table[:max(int(top), 1)],
        "drift_events": events[-20:],
        "slo_tail": slo[-8:],
    }


def _fmt_drift(s: Dict[str, Any]) -> str:
    lines: List[str] = []
    latest = s.get("latest")
    if latest is None:
        lines.append("no drift_flush records found (is "
                     "tpu_drift_flush_every armed and the stream/flight "
                     "dump from a serving run?)")
    else:
        lines.append(
            f"drift flushes: {s['flushes']}  latest: flush "
            f"#{latest.get('flush')} version={latest.get('version')!r} "
            f"window_rows={latest.get('window_rows')} "
            f"threshold={latest.get('threshold')}")
        sp = latest.get("score_psi")
        lines.append(f"score drift: psi="
                     f"{sp if sp is not None else '-'}"
                     + (" [DRIFTED]" if latest.get("score_drifted")
                        else ""))
        if s["psi_table"]:
            lines.append("")
            lines.append(f"{'feature':<24} {'psi':>10} {'kl':>10}  state")
            for row in s["psi_table"]:
                kl = row.get("kl")
                kls = f"{kl:>10.4f}" if kl is not None else f"{'-':>10}"
                lines.append(
                    f"{str(row['feature'])[:24]:<24} "
                    f"{row['psi']:>10.4f} {kls}"
                    f"  {'DRIFTED' if row['drifted'] else 'ok'}")
    if s["drift_events"]:
        lines.append("")
        lines.append("drift/SLO events (tail):")
        for rec in s["drift_events"]:
            rest = {k: v for k, v in rec.items()
                    if k not in ("event", "kind", "t", "seq")}
            lines.append(f"  {_kind(rec)}: {json.dumps(rest, default=str)}")
    if s["slo_tail"]:
        lines.append("")
        lines.append(f"{'good':>10} {'bad':>8} {'burn_5m':>9} "
                     f"{'burn_1h':>9}  alerting")
        for rec in s["slo_tail"]:
            lines.append(
                f"{int(rec.get('good_total', 0) or 0):>10} "
                f"{int(rec.get('bad_total', 0) or 0):>8} "
                f"{float(rec.get('burn_5m', 0) or 0):>9.3f} "
                f"{float(rec.get('burn_1h', 0) or 0):>9.3f}  "
                f"{bool(rec.get('alerting'))}")
    return "\n".join(lines)


def drift_main(argv: Sequence[str]) -> int:
    ap = argparse.ArgumentParser(
        prog="obs drift",
        description="latest serving drift flush: per-feature PSI table, "
                    "top drifted features, score drift, SLO burn-rate "
                    "tail (from tpu_metrics_path streams / flight dumps)")
    ap.add_argument("paths", nargs="+",
                    help="metrics-stream / flight-dump JSONL files")
    ap.add_argument("--top", type=int, default=10,
                    help="PSI table rows (default 10)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the summary as JSON instead of a table")
    args = ap.parse_args(argv)
    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        print(f"obs drift: no such file: {', '.join(missing)}",
              file=sys.stderr)
        return 2
    s = drift_summary(args.paths, top=args.top)
    if args.as_json:
        print(json.dumps(s, indent=1, default=str))
    else:
        print(_fmt_drift(s))
    return 0


def trace_main(argv: Sequence[str]) -> int:
    ap = argparse.ArgumentParser(
        prog="obs trace",
        description="per-phase DEVICE-time table from a tpu_trace_dir "
                    "profiler artifact (jax-free xplane parse)")
    ap.add_argument("trace_dir", help="the tpu_trace_dir a run wrote")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the analysis as JSON instead of a table")
    args = ap.parse_args(argv)
    from .tracing import analyze_trace_dir
    analysis = analyze_trace_dir(args.trace_dir)
    if analysis is None:
        print(f"obs trace: no xplane artifact under {args.trace_dir}",
              file=sys.stderr)
        return 2
    if args.as_json:
        print(json.dumps(analysis, indent=1, default=str))
    else:
        print(_fmt_trace(analysis))
    return 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    # subcommands ride in front of the legacy positional form
    # (`scripts/obs <files>` keeps summarizing, unchanged)
    if argv and argv[0] == "trace":
        return trace_main(argv[1:])
    if argv and argv[0] == "merge":
        return merge_main(argv[1:])
    if argv and argv[0] == "drift":
        return drift_main(argv[1:])
    ap = argparse.ArgumentParser(
        prog="obs", description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="+",
                    help="metrics-stream / flight-dump JSONL files")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the summary as JSON instead of a table")
    args = ap.parse_args(argv)
    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        print(f"obs: no such file: {', '.join(missing)}", file=sys.stderr)
        return 2
    summary = summarize(args.paths)
    if args.as_json:
        print(json.dumps(summary, indent=1, default=str))
    else:
        print(_fmt_table(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
