"""Summarize flight-recorder / metrics JSONL: the Common::Timer::Print.

The reference prints a per-phase wall-time table at process exit when
built with ``USE_TIMETAG`` (``Common::Timer::Print``,
include/LightGBM/utils/log.h). Here the equivalent table is derived
offline from the observability artifacts a run leaves behind — a
``tpu_metrics_path`` stream, a flight-recorder dump, or both:

    scripts/obs run_metrics.jsonl flight_1234.jsonl
    scripts/obs --json run_metrics.jsonl

prints per-phase host time share, phase-keyed compile totals, persistent-
cache hit/miss, collective-program byte totals (when the run captured
them via LGBM_TPU_COMM_ACCOUNTING), iteration throughput, and the tail
of notable events (faults, deadlines, restarts, swaps) — the post-mortem
read of a dead run, or the profile read of a healthy one.

This module is intentionally jax-free (plain json/os), so ``scripts/obs``
runs anywhere in milliseconds, including hosts without a backend.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Sequence

#: event kinds surfaced in the "notable events" tail
NOTABLE = ("fault_fire", "deadline", "retry", "crash",
           "training_interrupted", "swap_failed", "worker_restart",
           "snapshot_corrupt")


def _read_jsonl(path: str) -> List[Dict[str, Any]]:
    from .metrics import read_stream
    return read_stream(path)


def _kind(rec: Dict[str, Any]) -> str:
    return str(rec.get("kind") or rec.get("event") or "")


def summarize(paths: Sequence[str]) -> Dict[str, Any]:
    """Aggregate one or more JSONL artifacts into a summary dict."""
    records: List[Dict[str, Any]] = []
    for p in paths:
        records.extend(_read_jsonl(p))

    phase_times: Dict[str, Dict[str, float]] = {}
    compiles: Optional[Dict[str, Any]] = None
    cache: Optional[Dict[str, Any]] = None
    collectives: Dict[str, Dict[str, Any]] = {}
    iters = 0
    iter_seconds = 0.0
    notable: List[Dict[str, Any]] = []
    spans_seen: List[str] = []
    dump_header: Optional[Dict[str, Any]] = None

    for rec in records:
        k = _kind(rec)
        if k == "iteration":
            iters += 1
            iter_seconds += float(rec.get("seconds", 0.0) or 0.0)
            if isinstance(rec.get("compiles"), dict):
                compiles = rec["compiles"]     # cumulative: keep the last
            if isinstance(rec.get("cache"), dict):
                cache = rec["cache"]
        elif k in ("summary", "mark"):
            if isinstance(rec.get("phase_times"), dict):
                phase_times = rec["phase_times"]
            if isinstance(rec.get("compiles"), dict):
                compiles = rec["compiles"]
            if isinstance(rec.get("cache"), dict):
                cache = rec["cache"]
            if isinstance(rec.get("spans_seen"), list):
                spans_seen = sorted(set(spans_seen)
                                    | set(rec["spans_seen"]))
        elif k == "collective_program":
            collectives[str(rec.get("key"))] = {
                "bytes": rec.get("bytes"), "total": rec.get("total")}
        elif k == "flight_dump":
            dump_header = rec
        if k in NOTABLE:
            notable.append(rec)

    total_phase_s = sum(float(v.get("seconds", 0.0) or 0.0)
                        for v in phase_times.values()) or None
    return {
        "records": len(records),
        "iterations": iters,
        "iter_seconds_mean": (iter_seconds / iters) if iters else None,
        "phase_times": phase_times,
        "phase_total_seconds": total_phase_s,
        "compiles": compiles,
        "cache": cache,
        "collectives": collectives,
        "collective_bytes_total": sum(
            int(v.get("total") or 0) for v in collectives.values()) or None,
        "spans_seen": spans_seen,
        "notable": notable[-20:],
        "dump": dump_header,
    }


def _mark_index(records: List[Dict[str, Any]]) -> Dict[str, Dict[str, Any]]:
    """Last occurrence of each named ``mark`` record."""
    out: Dict[str, Dict[str, Any]] = {}
    for rec in records:
        if _kind(rec) == "mark" and rec.get("name"):
            out[str(rec["name"])] = rec
    return out


def _diff_compiles(a: Optional[Dict], b: Optional[Dict]) -> Dict[str, Any]:
    """b - a of two cumulative compile snapshots (phase-keyed)."""
    a, b = a or {}, b or {}

    def n(d, key):
        return int((d or {}).get(key, 0) or 0)

    phases = set((a.get("by_phase") or {})) | set((b.get("by_phase") or {}))
    by_phase = {}
    for p in sorted(phases):
        pa = (a.get("by_phase") or {}).get(p) or {}
        pb = (b.get("by_phase") or {}).get(p) or {}
        d = {"lowerings": n(pb, "lowerings") - n(pa, "lowerings"),
             "backend_compiles": (n(pb, "backend_compiles")
                                  - n(pa, "backend_compiles"))}
        if d["lowerings"] or d["backend_compiles"]:
            by_phase[p] = d
    return {"lowerings": n(b, "lowerings") - n(a, "lowerings"),
            "backend_compiles": (n(b, "backend_compiles")
                                 - n(a, "backend_compiles")),
            "by_phase": by_phase}


def bench_counters(path: str) -> Optional[Dict[str, Any]]:
    """Derive the BENCH-row counters from a metrics stream.

    Expects the bench marks ``warmup_start``/``warmup_end``/
    ``steady_end`` (each carrying a cumulative ``compiles``/``cache``
    snapshot). Returns None when the stream is missing or unmarked, so
    bench.py can fall back to its inline counters instead of recording a
    half-empty row."""
    if not path or not os.path.exists(path):
        return None
    records = _read_jsonl(path)
    marks = _mark_index(records)
    if not all(m in marks for m in ("warmup_start", "warmup_end",
                                    "steady_end")):
        return None
    w0, w1, s1 = (marks["warmup_start"], marks["warmup_end"],
                  marks["steady_end"])
    warm = _diff_compiles(w0.get("compiles"), w1.get("compiles"))
    steady = _diff_compiles(w1.get("compiles"), s1.get("compiles"))

    def cache_of(rec):
        c = rec.get("cache") or {}
        return {k: int(c.get(k, 0) or 0) for k in ("requests", "hits")}

    # cache counters over the WARMUP window, matching compile_events and
    # the inline warm_cache fallback — mixing windows would let a
    # steady-state compile skew the warm-round hits==requests comparison
    c0, c1 = cache_of(w0), cache_of(w1)
    requests = c1["requests"] - c0["requests"]
    hits = c1["hits"] - c0["hits"]
    return {
        "warmup_seconds": round(float(w1["t"]) - float(w0["t"]), 1),
        "compile_events": warm["lowerings"],
        "compile_events_by_phase": warm["by_phase"],
        "compile_events_steady": steady["lowerings"],
        "compile_cache": {"requests": requests, "hits": hits,
                          "misses": requests - hits},
    }


def _fmt_table(summary: Dict[str, Any]) -> str:
    lines: List[str] = []
    pt = summary["phase_times"]
    total = summary["phase_total_seconds"]
    lines.append(f"records: {summary['records']}  "
                 f"iterations: {summary['iterations']}"
                 + (f"  mean iter: {summary['iter_seconds_mean']:.4f}s"
                    if summary["iter_seconds_mean"] else ""))
    if pt:
        lines.append("")
        lines.append(f"{'phase':<20} {'seconds':>10} {'share':>7} "
                     f"{'count':>8}")
        for name, v in sorted(pt.items(),
                              key=lambda kv: -float(
                                  kv[1].get('seconds', 0) or 0)):
            s = float(v.get("seconds", 0.0) or 0.0)
            share = (s / total) if total else 0.0
            lines.append(f"{name:<20} {s:>10.3f} {share:>6.1%} "
                         f"{int(v.get('count', 0) or 0):>8}")
    comp = summary["compiles"]
    if comp:
        lines.append("")
        lines.append(f"compiles: {comp.get('lowerings', 0)} lowerings, "
                     f"{comp.get('backend_compiles', 0)} backend")
        for p, d in sorted((comp.get("by_phase") or {}).items()):
            lines.append(f"  {p:<18} {d.get('lowerings', 0):>4} lowerings "
                         f"{d.get('backend_compiles', 0):>4} backend")
    cache = summary["cache"]
    if cache:
        lines.append(f"compile cache: {cache.get('hits', 0)}/"
                     f"{cache.get('requests', 0)} hits")
    if summary["collectives"]:
        lines.append("")
        lines.append(f"collective programs "
                     f"({summary['collective_bytes_total']} bytes/step "
                     f"total):")
        for key, v in sorted(summary["collectives"].items()):
            lines.append(f"  {key:<24} {v.get('total')} bytes "
                         f"{json.dumps(v.get('bytes'), default=str)}")
    if summary["spans_seen"]:
        lines.append("")
        lines.append("spans seen: " + ", ".join(summary["spans_seen"]))
    if summary["dump"]:
        d = summary["dump"]
        lines.append("")
        lines.append(f"flight dump: reason={d.get('reason')!r} "
                     f"events={d.get('events')} dropped={d.get('dropped')}")
    if summary["notable"]:
        lines.append("")
        lines.append("notable events (tail):")
        for rec in summary["notable"]:
            k = _kind(rec)
            rest = {key: v for key, v in rec.items()
                    if key not in ("kind", "event", "t", "seq")}
            lines.append(f"  {k}: {json.dumps(rest, default=str)}")
    return "\n".join(lines)


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="obs", description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="+",
                    help="metrics-stream / flight-dump JSONL files")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the summary as JSON instead of a table")
    args = ap.parse_args(argv)
    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        print(f"obs: no such file: {', '.join(missing)}", file=sys.stderr)
        return 2
    summary = summarize(args.paths)
    if args.as_json:
        print(json.dumps(summary, indent=1, default=str))
    else:
        print(_fmt_table(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
