"""Metrics plane: per-iteration JSONL stream + Prometheus-text exposition.

Two consumers, one schema:

* **Offline** — ``tpu_metrics_path`` arms a per-run JSONL stream. The
  booster emits one ``iteration`` record per update (wall seconds +
  CUMULATIVE phase-keyed compile counts + persistent-cache counters),
  engine.train adds run-level marks and a final ``summary`` (host
  phase-time table, span names seen). bench.py arms the same stream and
  derives its BENCH-row counters (``warmup_seconds``/``compile_events``/
  cache hit-miss) from it instead of re-deriving them inline, and
  ``scripts/obs`` prints the ``Common::Timer::Print``-style rollup.
* **Online** — :class:`MetricsServer` serves the same numbers as
  Prometheus text exposition over stdlib HTTP (``GET /metrics``, plus
  ``GET /healthz`` JSON) from a PredictionServer (``--metrics-port`` on
  ``scripts/serve``). No new dependencies: ``http.server`` + a flat
  gauge rendering.

Stream records are self-describing dicts: ``{"t": <unix>, "kind": ...,
...}``. Compile counters are cumulative so a consumer can diff any two
records without having observed the events in between (the bench warmup
window is exactly such a diff).
"""
from __future__ import annotations

import http.server
import json
import numbers
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

#: default prefix for exposed metric names
PROM_PREFIX = "lgbm_tpu_"


class MetricsStream:
    """Append-only JSONL metrics stream (one file per run)."""

    def __init__(self, path: str):
        self.path = str(path)
        self._mu = threading.Lock()
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        # truncate: the stream describes THIS run (resumed runs re-emit
        # from their restored iteration; the consumer keys on the records,
        # not on line position)
        self._fh = open(self.path, "w", encoding="utf-8")

    def emit(self, kind: str, **fields: Any) -> None:
        """Write one record; flushed per record so a dying process leaves
        everything it measured.

        Best-effort by contract: telemetry must never kill the run it
        observes. A write failure (ENOSPC, the stream's filesystem going
        away mid-run) warns once, closes the stream, and drops further
        records — it must NOT raise out of a training finally-block and
        replace the in-flight exception."""
        rec = {"t": round(time.time(), 6), "kind": kind}
        rec.update(fields)
        with self._mu:
            if self._fh.closed:
                return
            try:
                self._fh.write(json.dumps(rec, default=str) + "\n")
                self._fh.flush()
            except Exception as err:  # noqa: BLE001 - telemetry is best-effort
                try:
                    self._fh.close()
                except Exception:
                    pass
                from ..utils import log
                log.warning(f"metrics stream {self.path} failed "
                            f"({err}); disabling for this run")

    def close(self) -> None:
        with self._mu:
            if not self._fh.closed:
                self._fh.close()


#: per-path shared streams; None marks a path that failed to open (the
#: failure is cached so it is not retried per booster)
_streams: Dict[str, Optional[MetricsStream]] = {}
_streams_mu = threading.Lock()


def stream_for(path) -> Optional[MetricsStream]:
    """The shared per-path stream (booster ticks and engine marks write
    to ONE file); empty/unset paths return None.

    A stream that CLOSED (emit failure, explicit close) is returned
    as-is, not rebuilt: ``MetricsStream`` opens with truncating ``'w'``,
    so resurrecting it would destroy every record the run already
    flushed — a closed stream's ``emit`` is a safe no-op instead."""
    p = str(path or "").strip()
    if not p:
        return None
    key = os.path.abspath(p)
    with _streams_mu:
        if key in _streams:
            return _streams[key]
        try:
            s = MetricsStream(p)
        except OSError as err:
            # telemetry must never kill the run it observes: an
            # unwritable path (read-only checkout, full disk) warns once
            # and the run proceeds streamless; the None is cached so the
            # open() is not retried per booster
            from ..utils import log
            log.warning(f"cannot open metrics stream {p} ({err}); "
                        "continuing without it")
            s = None
        _streams[key] = s
        return s


def read_stream(path: str) -> List[Dict[str, Any]]:
    """Parse a JSONL telemetry artifact (metrics stream or flight dump —
    same line shape), skipping blank/torn/non-record lines. The ONE
    tolerant reader: flight.read_dump and summarize delegate here."""
    out: List[Dict[str, Any]] = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict):
                out.append(rec)
    return out


# -- Prometheus text exposition ---------------------------------------------
def escape_label_value(value: Any) -> str:
    """Escape one label VALUE per the Prometheus text exposition format:
    backslash, double-quote and newline must be escaped (in that order —
    escaping the backslash last would re-break the other two). Label
    values are arbitrary UTF-8 (feature names, model versions come from
    user data), so this is mandatory hygiene, not polish."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _label_name(name: str) -> str:
    """Sanitize a label NAME to the [a-zA-Z_][a-zA-Z0-9_]* charset (label
    names, unlike values, have no escape syntax)."""
    out = "".join(c if c.isalnum() or c == "_" else "_" for c in str(name))
    if not out or not (out[0].isalpha() or out[0] == "_"):
        out = "_" + out
    return out


def render_labels(labels: Dict[str, Any]) -> str:
    """``{k="v",...}`` with escaped values; empty dict renders nothing."""
    if not labels:
        return ""
    inner = ",".join(f'{_label_name(k)}="{escape_label_value(v)}"'
                     for k, v in labels.items())
    return "{" + inner + "}"


def render_gauges(name: str,
                  series: List[tuple]) -> List[str]:
    """One gauge family: a TYPE line plus one sample per
    ``(labels_dict, value)`` entry."""
    lines = [f"# TYPE {name} gauge"]
    for labels, value in series:
        lines.append(f"{name}{render_labels(labels)} {float(value):.17g}")
    return lines


def render_histogram(name: str, labels: Dict[str, Any],
                     bucket_bounds, counts, total_sum: float,
                     total_count: int) -> List[str]:
    """One Prometheus histogram: per-bucket (NON-cumulative) ``counts``
    — one per bound plus a final overflow cell — rendered as the
    cumulative ``_bucket{le=}`` series the format requires, with
    ``+Inf``, ``_sum`` and ``_count``."""
    lines = [f"# TYPE {name} histogram"]
    cum = 0
    for bound, c in zip(bucket_bounds, counts):
        cum += int(c)
        lab = render_labels({**labels, "le": format(float(bound), "g")})
        lines.append(f"{name}_bucket{lab} {cum}")
    lab = render_labels({**labels, "le": "+Inf"})
    lines.append(f"{name}_bucket{lab} {int(total_count)}")
    base = render_labels(labels)
    lines.append(f"{name}_sum{base} {float(total_sum):.17g}")
    lines.append(f"{name}_count{base} {int(total_count)}")
    return lines


def _flatten(prefix: str, value: Any, out: Dict[str, float]) -> None:
    if isinstance(value, bool):
        out[prefix] = 1.0 if value else 0.0
    elif isinstance(value, numbers.Number):
        out[prefix] = float(value)
    elif isinstance(value, dict):
        for k, v in value.items():
            key = str(k).replace("-", "_").replace(" ", "_").replace(
                ".", "_").replace("/", "_")
            _flatten(f"{prefix}_{key}" if prefix else key, v, out)
    elif isinstance(value, (list, tuple)):
        out[f"{prefix}_count"] = float(len(value))
    # strings/None: not a metric (they live in /healthz)


def flatten_metrics(tree: Dict[str, Any]) -> Dict[str, float]:
    """Flatten a nested dict into ``name_path -> float`` gauges; lists
    become ``_count``, strings are dropped (they belong in /healthz)."""
    out: Dict[str, float] = {}
    _flatten("", tree, out)
    return {k.lstrip("_"): v for k, v in out.items()}


def render_prometheus(tree: Dict[str, Any],
                      prefix: str = PROM_PREFIX) -> str:
    """Prometheus text exposition (text/plain; version=0.0.4) of a nested
    numeric dict. Everything is exposed as a gauge — counters here are
    cumulative process-lifetime values, which Prometheus rate() handles
    identically, and gauge is the type that is never a lie."""
    lines: List[str] = []
    for name, value in sorted(flatten_metrics(tree).items()):
        full = f"{prefix}{name}"
        lines.append(f"# TYPE {full} gauge")
        lines.append(f"{full} {value:.17g}")
    return "\n".join(lines) + "\n"


class MetricsServer:
    """Pull-based exposition endpoint: stdlib HTTP, two routes.

    ``provider()`` returns the nested metrics dict; ``GET /metrics``
    renders it as Prometheus text, ``GET /healthz`` (and ``/health``)
    returns it as JSON. ``text_extra`` (optional) returns pre-rendered
    exposition lines appended to ``/metrics`` — the labeled series
    (latency histograms, per-feature drift PSI) the flat gauge tree
    cannot carry. ``port=0`` binds an ephemeral port (tests); ``.port``
    reports the bound one. Serving runs on a daemon thread — ``stop()``
    (or the owning server's close) shuts it down."""

    def __init__(self, provider: Callable[[], Dict[str, Any]],
                 port: int = 0, host: str = "127.0.0.1",
                 prefix: str = PROM_PREFIX,
                 text_extra: Optional[Callable[[], str]] = None):
        self._provider = provider
        self._prefix = prefix
        self._text_extra = text_extra
        outer = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - stdlib API
                try:
                    tree = outer._provider()
                    if self.path.startswith("/metrics"):
                        text = render_prometheus(tree, outer._prefix)
                        if outer._text_extra is not None:
                            text += outer._text_extra()
                        body = text.encode()
                        ctype = "text/plain; version=0.0.4; charset=utf-8"
                    elif self.path.startswith(("/healthz", "/health")):
                        body = json.dumps(
                            tree, default=str, indent=1).encode()
                        ctype = "application/json"
                    else:
                        self.send_error(404)
                        return
                    self.send_response(200)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except Exception as err:  # noqa: BLE001 - report, not die
                    try:
                        self.send_error(500, str(err)[:200])
                    except Exception:
                        pass

            def log_message(self, *a):  # silence per-request stderr spam
                return

        self._httpd = http.server.ThreadingHTTPServer((host, int(port)),
                                                      _Handler)
        try:
            self._httpd.daemon_threads = True
            self.port = int(self._httpd.server_address[1])
            self._thread = threading.Thread(
                target=self._httpd.serve_forever, daemon=True,
                name=f"lgbm-tpu-metrics:{self.port}")
            self._thread.start()
        except BaseException:
            # a raise after the socket is bound would drop the half-built
            # server with the port still held and no handle to close it
            # (R012 constructor exception edge)
            self._httpd.server_close()
            raise

    def stop(self) -> None:
        # shutdown and server_close in SEPARATE trys: a shutdown raise
        # must not skip closing the listening socket (R012), and the
        # serve thread is joined so stop() really quiesces the process
        try:
            self._httpd.shutdown()
        except Exception:  # noqa: BLE001 - idempotent shutdown
            pass
        try:
            self._httpd.server_close()
        except Exception:  # noqa: BLE001 - idempotent shutdown
            pass
        thread = getattr(self, "_thread", None)
        if thread is not None and thread.is_alive():
            thread.join(timeout=5.0)
