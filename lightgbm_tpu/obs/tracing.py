"""Device-time trace analytics: the profiler artifact, parsed honestly.

Every number the telemetry plane reported for the device side before
this module was a *host* wall clock wrapped around async dispatch —
exactly what tpulint R009 exists to distrust. The profiler is the one
component that measures real device time, and ``trace_session``
(obs/spans.py) already makes it write its artifact under
``tpu_trace_dir``:

    <tpu_trace_dir>/plugins/profile/<run>/<host>.xplane.pb

This module parses that artifact OFFLINE (after the session closed,
never on the hot path — tpulint R009c pins any import of it from
jit-reachable code) and maps the timed events back to the PR 10 span
taxonomy through the ``named_scope`` phase names the lowered programs
carry, producing:

* a per-phase **device**-time table (``hist_build``,
  ``collective_reduce``, ``split_scan``, ...) — emitted side by side
  with the host phase table (``device_seconds`` vs ``host_seconds``) in
  the metrics-stream summary, so host-dispatch skew is visible instead
  of silently reported as compute;
* per-collective op durations (the measured counterpart of the byte
  model in ``analysis/contracts/*.json`` — obs/ledger.py divides them);
* an MXU / comm / idle decomposition of the device timeline.

Artifact mechanics, all jax-free (scripts/obs runs this without a
backend):

* ``xplane.pb`` is a ``tensorflow.profiler.XSpace`` protobuf. A ~60-line
  generic wire-format reader walks it with the field numbers below — no
  protobuf dependency. Planes hold lines (one per device stream / host
  thread), lines hold events (``offset_ps``/``duration_ps``), and event
  metadata carries names.
* The full ``jit(step)/.../hist_build/...`` scope path lives in the HLO
  proto each module's metadata entry embeds (``OpMetadata.op_name`` per
  instruction), NOT in the timed event names — those are bare HLO
  instruction names (``fusion.3``, ``all-reduce.1``). The parser builds
  the instruction -> scoped-op-name map from the embedded HLO protos and
  resolves every timed event through it.
* On TPU/GPU the timed events live on ``/device:...`` planes. On CPU
  there is no device plane; XLA's compute-pool threads still record the
  per-instruction executions on the host plane, so the analyzer falls
  back to host-plane events that resolve through the HLO instruction map
  (``source: "host-xla"`` marks the fallback — dispatch skew included,
  but per-phase attribution is real).
"""
from __future__ import annotations

import glob
import os
from typing import Any, Dict, Iterator, List, Optional, Tuple

#: the complete phase-name taxonomy. Canonical HERE (jax-free) so both
#: the trace analytics and scripts/obs can name phases without a
#: backend; obs/spans.py re-exports it (tests and engine key on
#: ``spans.SPAN_TAXONOMY``).
SPAN_TAXONOMY = (
    "binning", "gradient", "hist_build", "collective_reduce", "split_scan",
    "partition", "checkpoint_write", "predict_warmup", "serve_tick",
    "autotune", "featurize", "contrib",
)

#: HLO opcode/name fragments that mean "communication"
_COLLECTIVE_TOKENS = (
    "all-reduce", "reduce-scatter", "all-gather", "all-to-all",
    "collective-permute", "collective-broadcast", "send", "recv",
)
#: opcodes whose time is MXU (systolic-array) work
_MXU_OPCODES = {"dot", "convolution"}
_MXU_TOKENS = ("dot", "conv", "matmul")

_PS = 1e-12   # picoseconds -> seconds


# -- protobuf wire-format reader ---------------------------------------------
def _varint(buf: bytes, i: int) -> Tuple[int, int]:
    r = 0
    s = 0
    while True:
        b = buf[i]
        i += 1
        r |= (b & 0x7F) << s
        if not b & 0x80:
            return r, i
        s += 7


def _iter_fields(buf: bytes) -> Iterator[Tuple[int, int, Any]]:
    """Yield ``(field_number, wire_type, value)`` over one message.

    Wire types: 0 varint (int), 2 length-delimited (bytes), 5/1 fixed
    32/64 (raw bytes). Anything else is a parse error — the caller
    treats the blob as not-a-message.
    """
    i, n = 0, len(buf)
    while i < n:
        key, i = _varint(buf, i)
        fn, wt = key >> 3, key & 7
        if wt == 0:
            v, i = _varint(buf, i)
        elif wt == 2:
            ln, i = _varint(buf, i)
            if i + ln > n:
                raise ValueError("truncated length-delimited field")
            v = buf[i:i + ln]
            i += ln
        elif wt == 5:
            v = buf[i:i + 4]
            i += 4
        elif wt == 1:
            v = buf[i:i + 8]
            i += 8
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield fn, wt, v


def _utf8(b: bytes) -> str:
    return b.decode("utf-8", errors="replace")


# -- HLO proto: instruction name -> (scoped op_name, opcode) -----------------
def _parse_op_metadata(buf: bytes) -> Tuple[str, str]:
    """OpMetadata: op_type=1, op_name=2 (the full scope path)."""
    op_type = op_name = ""
    for fn, wt, v in _iter_fields(buf):
        if wt != 2:
            continue
        if fn == 1:
            op_type = _utf8(v)
        elif fn == 2:
            op_name = _utf8(v)
    return op_type, op_name


def _parse_hlo_instructions(buf: bytes, out: Dict[str, Tuple[str, str]]
                            ) -> int:
    """Walk an ``xla.HloProto`` blob: hlo_module=1 -> computations=3 ->
    instructions=2 -> {name=1, opcode=2, metadata=7}. Adds
    ``instr_name -> (scoped_op_name, opcode)`` entries; returns how many
    instructions were seen (0 = the blob was not an HLO proto)."""
    seen = 0
    try:
        for fn, wt, v in _iter_fields(buf):
            if fn != 1 or wt != 2:       # hlo_module
                continue
            for f2, w2, v2 in _iter_fields(v):
                if f2 != 3 or w2 != 2:   # computations
                    continue
                for f3, w3, v3 in _iter_fields(v2):
                    if f3 != 2 or w3 != 2:   # instructions
                        continue
                    name = opcode = ""
                    op_name = ""
                    for f4, w4, v4 in _iter_fields(v3):
                        if w4 != 2:
                            continue
                        if f4 == 1:
                            name = _utf8(v4)
                        elif f4 == 2:
                            opcode = _utf8(v4)
                        elif f4 == 7:
                            _, op_name = _parse_op_metadata(v4)
                    if name:
                        seen += 1
                        # scope path falls back to the bare name
                        out[name] = (op_name or name, opcode)
    except (ValueError, IndexError):
        return 0
    return seen


# -- XSpace parsing ----------------------------------------------------------
class XLine:
    __slots__ = ("name", "timestamp_ns", "events")

    def __init__(self) -> None:
        self.name = ""
        self.timestamp_ns = 0
        # (metadata_id, offset_ps, duration_ps)
        self.events: List[Tuple[int, int, int]] = []


class XPlane:
    __slots__ = ("name", "lines", "event_names", "hlo_map")

    def __init__(self) -> None:
        self.name = ""
        self.lines: List[XLine] = []
        self.event_names: Dict[int, str] = {}
        # instruction name -> (scoped op_name, opcode), from embedded
        # HLO protos in this plane's event metadata
        self.hlo_map: Dict[str, Tuple[str, str]] = {}


def _parse_event(buf: bytes) -> Tuple[int, int, int]:
    """XEvent: metadata_id=1, offset_ps=2, duration_ps=3."""
    mid = off = dur = 0
    for fn, wt, v in _iter_fields(buf):
        if wt != 0:
            continue
        if fn == 1:
            mid = v
        elif fn == 2:
            off = v
        elif fn == 3:
            dur = v
    return mid, off, dur


def _parse_line(buf: bytes) -> XLine:
    """XLine: name=2, timestamp_ns=3, events=4, display_name=11."""
    line = XLine()
    display = ""
    for fn, wt, v in _iter_fields(buf):
        if fn == 2 and wt == 2:
            line.name = _utf8(v)
        elif fn == 11 and wt == 2:
            display = _utf8(v)
        elif fn == 3 and wt == 0:
            line.timestamp_ns = v
        elif fn == 4 and wt == 2:
            line.events.append(_parse_event(v))
    line.name = line.name or display
    return line


def _parse_event_metadata(buf: bytes, plane: XPlane) -> None:
    """map<int64, XEventMetadata> entry: key=1, value=2. XEventMetadata:
    id=1, name=2, stats=5; any bytes stat that parses as an HLO proto
    feeds the plane's instruction map."""
    key = None
    meta = None
    for fn, wt, v in _iter_fields(buf):
        if fn == 1 and wt == 0:
            key = v
        elif fn == 2 and wt == 2:
            meta = v
    if meta is None:
        return
    name = ""
    for fn, wt, v in _iter_fields(meta):
        if fn == 1 and wt == 0 and key is None:
            key = v
        elif fn == 2 and wt == 2:
            name = _utf8(v)
        elif fn == 5 and wt == 2:
            # XStat: value oneof; bytes_value=6 may embed an HloProto
            for f2, w2, v2 in _iter_fields(v):
                if f2 == 6 and w2 == 2 and len(v2) > 16:
                    _parse_hlo_instructions(v2, plane.hlo_map)
    if key is not None and name:
        plane.event_names[key] = name


def parse_xspace(data: bytes) -> List[XPlane]:
    """Parse serialized XSpace bytes into planes (lines + name tables)."""
    planes: List[XPlane] = []
    for fn, wt, v in _iter_fields(data):
        if fn != 1 or wt != 2:           # XSpace.planes
            continue
        plane = XPlane()
        for f2, w2, v2 in _iter_fields(v):
            if f2 == 2 and w2 == 2:
                plane.name = _utf8(v2)
            elif f2 == 3 and w2 == 2:
                plane.lines.append(_parse_line(v2))
            elif f2 == 4 and w2 == 2:
                _parse_event_metadata(v2, plane)
        planes.append(plane)
    return planes


# -- analytics ---------------------------------------------------------------
def phase_of(scoped_name: str) -> Optional[str]:
    """First taxonomy token appearing in a scoped op name, scanned in
    path order so the OUTERMOST phase scope wins (``.../hist_build/
    jit(cumsum)/...`` is hist_build even if an inner scope matches
    another token)."""
    best: Tuple[int, Optional[str]] = (len(scoped_name) + 1, None)
    for token in SPAN_TAXONOMY:
        i = scoped_name.find(token)
        if i >= 0 and i < best[0]:
            best = (i, token)
    return best[1]


def _is_collective(name: str, opcode: str) -> bool:
    base = (opcode or name).lower()
    return any(t in base for t in _COLLECTIVE_TOKENS)


def _is_mxu(name: str, opcode: str) -> bool:
    if opcode in _MXU_OPCODES:
        return True
    base = name.lower()
    return any(t in base for t in _MXU_TOKENS)


def analyze_planes(planes: List[XPlane]) -> Dict[str, Any]:
    """Aggregate parsed planes into the device-time analysis dict.

    Device planes (``/device:...``) are authoritative when present;
    otherwise host-plane events that resolve through the HLO instruction
    map stand in (CPU backend — source ``host-xla``).
    """
    # one shared instruction map: the metadata plane holds the HLO protos
    # even when the timed events live on another plane
    hlo_map: Dict[str, Tuple[str, str]] = {}
    for plane in planes:
        hlo_map.update(plane.hlo_map)

    device_planes = [p for p in planes if p.name.startswith("/device:")]
    source = "device" if device_planes else "host-xla"
    use = device_planes or planes

    phases: Dict[str, Dict[str, float]] = {}
    collectives: Dict[str, Dict[str, float]] = {}
    busy = mxu = comm = 0.0
    unattributed = 0.0
    lanes = 0
    span_min: Optional[float] = None
    span_max: Optional[float] = None

    def _instr_base(event_name: str) -> str:
        # profiler event names may suffix the instruction (".clone") or
        # wrap it; resolve exact first, then the dotted stem
        if event_name in hlo_map:
            return event_name
        stem = event_name.split("/")[-1]
        if stem in hlo_map:
            return stem
        if stem.endswith(".clone") and stem[:-6] in hlo_map:
            return stem[:-6]
        return ""

    for plane in use:
        # device planes carry DERIVED lines next to the op stream ("XLA
        # Modules" module-level rollups, "Steps", "Framework Name
        # Scope") whose events re-describe the same time — summing every
        # line would double-count. When an "XLA Ops" line exists, it is
        # the one authoritative op timeline per stream.
        lines = plane.lines
        if source == "device":
            op_lines = [ln for ln in lines if "XLA Ops" in (ln.name or "")]
            lines = op_lines or lines
        for line in lines:
            lane_used = False
            for mid, off, dur in line.events:
                name = plane.event_names.get(mid, "")
                if not name:
                    continue
                instr = _instr_base(name)
                if source == "host-xla" and not instr:
                    # host fallback: only REAL XLA op executions count —
                    # python frames and pool bookkeeping are not device
                    # time
                    continue
                scoped, opcode = hlo_map.get(instr, ("", ""))
                scoped = scoped or name
                secs = dur * _PS
                t0 = line.timestamp_ns * 1e-9 + off * _PS
                span_min = t0 if span_min is None else min(span_min, t0)
                span_max = (t0 + secs if span_max is None
                            else max(span_max, t0 + secs))
                lane_used = True
                busy += secs
                phase = phase_of(scoped)
                if phase is None:
                    unattributed += secs
                else:
                    d = phases.setdefault(
                        phase, {"device_seconds": 0.0, "events": 0})
                    d["device_seconds"] += secs
                    d["events"] += 1
                if _is_collective(scoped if not instr else instr, opcode):
                    key = (instr or name).split(".")[0] or name
                    c = collectives.setdefault(
                        key, {"seconds": 0.0, "count": 0})
                    c["seconds"] += secs
                    c["count"] += 1
                    comm += secs
                elif _is_mxu(scoped if not instr else instr, opcode):
                    mxu += secs
            if lane_used:
                lanes += 1

    total = (span_max - span_min) if span_min is not None else 0.0
    # spans that LOWERED: taxonomy tokens present anywhere in the scoped
    # op names of the compiled modules (whether or not their ops were
    # sampled into timed events)
    lowered = sorted({p for scoped, _ in hlo_map.values()
                     for p in (phase_of(scoped),) if p})
    for d in phases.values():
        d["device_seconds"] = round(d["device_seconds"], 9)
    return {
        "source": source,
        "lanes": lanes,
        "phases": phases,
        "unattributed_seconds": round(unattributed, 9),
        "collectives": {k: {"seconds": round(v["seconds"], 9),
                            "count": int(v["count"])}
                        for k, v in collectives.items()},
        "decomposition": {
            "total_seconds": round(total, 9),
            "busy_seconds": round(busy, 9),
            "mxu_seconds": round(mxu, 9),
            "comm_seconds": round(comm, 9),
            "idle_seconds": round(max(0.0, total - busy), 9),
        },
        "spans_lowered": lowered,
    }


def find_xplane_files(trace_dir: str) -> List[str]:
    """``*.xplane.pb`` files of the NEWEST run under ``trace_dir``
    (``plugins/profile/<run>/``; a bare directory of .pb files also
    works)."""
    runs = sorted(glob.glob(os.path.join(
        trace_dir, "plugins", "profile", "*")))
    candidates = ([runs[-1]] if runs else []) + [trace_dir]
    for d in candidates:
        files = sorted(glob.glob(os.path.join(d, "*.xplane.pb")))
        if files:
            return files
    return []


def analyze_trace_dir(trace_dir: str) -> Optional[Dict[str, Any]]:
    """Parse + analyze the newest trace run under ``trace_dir``; None
    when no artifact exists. Never raises on a torn artifact — the
    analytics run on post-mortem paths too."""
    files = find_xplane_files(trace_dir)
    if not files:
        return None
    planes: List[XPlane] = []
    for path in files:
        try:
            with open(path, "rb") as fh:
                planes.extend(parse_xspace(fh.read()))
        except (OSError, ValueError, IndexError):
            continue
    if not planes:
        return None
    out = analyze_planes(planes)
    out["trace_dir"] = trace_dir
    out["files"] = [os.path.basename(f) for f in files]
    return out
