"""Phase-named spans: one API, two faces (device trace names + host timing).

The reference attributes wall time to every training phase through its
``USE_TIMETAG`` ``Common::Timer`` registry (include/LightGBM/utils/log.h:
``global_timer.Print()`` at process exit). On TPU that design splits in
two, because the two interesting clocks live in different places:

* **Device time** belongs to the profiler. A span entered while jax is
  TRACING wraps the region in ``jax.named_scope``, so the lowered HLO ops
  carry the phase name and the Perfetto/TensorBoard trace that
  ``tpu_trace_dir`` emits shows ``hist_build`` / ``split_scan`` /
  ``collective_reduce`` lanes instead of a wall of fused ops. This costs
  nothing at runtime — the scope only exists at trace time.
* **Host time** belongs to the orchestration loop. A span entered outside
  tracing (checkpoint writes, serve ticks, warmup rungs) wraps the region
  in ``jax.profiler.TraceAnnotation`` and accumulates wall time into the
  per-phase table that :mod:`..obs.summarize` prints — the
  ``Common::Timer::Print`` analogue. Host timing around ASYNC dispatch
  measures dispatch, not device work (tpulint R009 exists to keep naive
  timing out of jit-reachable code); host spans are therefore placed only
  at the declared tick sites, where the host genuinely blocks.

Zero-cost-when-disabled contract: with no trace session active,
``span(name)`` outside tracing returns one shared no-op context manager —
two attribute reads, no allocation. Enablement comes from
:func:`trace_session` (the ``tpu_trace_dir``/``tpu_trace_mode`` context
engine.train holds for the whole run): ``mode="full"`` starts a real
``jax.profiler.trace`` AND enables host spans; ``mode="annotations"``
enables the spans (device names + host phase table) without the profiler
— the cheap always-on-able flavor.

Span taxonomy (every name a device program or tick site carries):

========================  ==================================================
``binning``               io/binning.bin_columns — raw values -> bin codes
                          (dataset construct AND the serve-time bin_matrix)
``gradient``              objective gradients/hessians for the iteration
``hist_build``            per-leaf histogram accumulation (all engines)
``collective_reduce``     psum/psum_scatter of histograms over the mesh
``split_scan``            best-split scan over the histogram bins
``partition``             row partition / routing after a split
``checkpoint_write``      io/checkpoint.write_snapshot atomic tick
``predict_warmup``        one serving-ladder rung warm (basic.py)
``serve_tick``            one coalescer micro-batch device dispatch
``autotune``              the startup engine microbench sweep
                          (engines/autotune.py — strictly pre-steady-state)
========================  ==================================================
"""
from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Iterator, Optional, Set

import jax

#: the complete phase-name taxonomy (tests assert a traced+served run
#: touches every one of these). Canonical copy lives in obs/tracing.py
#: (jax-free, so scripts/obs can attribute trace phases with no backend);
#: re-exported here because spans is the producer side of the same names.
from .tracing import SPAN_TAXONOMY  # noqa: E402,F401

_TRACE_MODES = ("full", "annotations")

_mu = threading.Lock()
_enabled = 0                      # nesting count of enabling sessions
_seen: Set[str] = set()           # span names entered (host) or traced
_seen_n: Dict[str, int] = {}      # per-name entry counts (for per-run
#                                   deltas: names are a SET, so a rerun
#                                   of the same spans is invisible to
#                                   set difference — counts are not)
_phase_s: Dict[str, float] = {}   # host-span wall seconds by name
_phase_n: Dict[str, int] = {}     # host-span entry counts by name


def _mark_seen(name: str) -> None:
    _seen.add(name)
    _seen_n[name] = _seen_n.get(name, 0) + 1


def _trace_state_clean() -> bool:
    try:
        return jax.core.trace_state_clean()
    except Exception:  # pragma: no cover - future-jax fallback: assume host
        return True


class _NullSpan:
    """Shared no-op span (the disabled fast path)."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False


_NULL = _NullSpan()


class _TracedSpan:
    """Span entered under a jax trace: pure ``named_scope``.

    Runs only at trace time — the name is baked into the lowered ops'
    metadata (the profiler groups device time under it) and costs nothing
    when the compiled program executes. Recording into the seen-set here
    is the honest signal that the DEVICE PROGRAM carries the name, not
    merely that host code passed by.
    """

    __slots__ = ("_scope",)

    def __init__(self, name: str):
        with _mu:
            _mark_seen(name)
        self._scope = jax.named_scope(name)

    def __enter__(self) -> "_TracedSpan":
        self._scope.__enter__()
        return self

    def __exit__(self, *exc) -> bool:
        self._scope.__exit__(*exc)
        return False


class _HostSpan:
    """Span entered on the host: profiler annotation + phase-time entry."""

    __slots__ = ("_name", "_ann", "_t0")

    def __init__(self, name: str):
        self._name = name
        self._ann = jax.profiler.TraceAnnotation(name)

    def __enter__(self) -> "_HostSpan":
        self._ann.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        dt = time.perf_counter() - self._t0
        self._ann.__exit__(*exc)
        with _mu:
            _mark_seen(self._name)
            _phase_s[self._name] = _phase_s.get(self._name, 0.0) + dt
            _phase_n[self._name] = _phase_n.get(self._name, 0) + 1
        return False


def span(name: str):
    """The phase span for ``name`` — see the module docstring.

    Under tracing: a ``named_scope`` (always, enablement aside — trace
    time is the only chance to name the device ops, and it is free at
    runtime). On the host: a timing+annotation span when a trace session
    is active, else the shared no-op.
    """
    if not _trace_state_clean():
        return _TracedSpan(name)
    if _enabled:
        return _HostSpan(name)
    return _NULL


def annotations_enabled() -> bool:
    return bool(_enabled)


def active_sessions() -> int:
    """Currently-entered ``trace_session`` nesting depth. The resource
    witness (guards.resource_witness) reads this: a scope that exits
    with a higher depth than it entered leaked a profiler session."""
    with _mu:
        return int(_enabled)


def enable_annotations() -> None:
    global _enabled
    with _mu:
        _enabled += 1


def disable_annotations() -> None:
    global _enabled
    with _mu:
        _enabled = max(0, _enabled - 1)


def seen_spans() -> Set[str]:
    """Span names observed so far (traced into a program, or entered on
    the host inside a session)."""
    with _mu:
        return set(_seen)


def phase_times() -> Dict[str, Dict[str, float]]:
    """Host-span wall time by phase: ``{name: {seconds, count}}``.

    Process-cumulative — per-RUN tables come from
    :func:`phase_times_since` (engine.train snapshots at run start so
    two runs in one process don't double-count each other's seconds)."""
    with _mu:
        return {k: {"seconds": _phase_s[k], "count": _phase_n.get(k, 0)}
                for k in sorted(_phase_s)}


def phase_times_since(baseline: Dict[str, Dict[str, float]]
                      ) -> Dict[str, Dict[str, float]]:
    """The phase-time delta accumulated after ``baseline`` (a prior
    :func:`phase_times` snapshot); zero-delta phases are dropped."""
    out: Dict[str, Dict[str, float]] = {}
    for name, cur in phase_times().items():
        base = baseline.get(name, {})
        secs = cur["seconds"] - float(base.get("seconds", 0.0))
        cnt = cur["count"] - int(base.get("count", 0))
        if secs > 0.0 or cnt > 0:
            out[name] = {"seconds": secs, "count": cnt}
    return out


def seen_counts() -> Dict[str, int]:
    """Per-name span entry counts (the per-run-delta baseline shape)."""
    with _mu:
        return dict(_seen_n)


def seen_since(baseline: Dict[str, int]) -> Set[str]:
    """Span names entered after ``baseline`` (a prior
    :func:`seen_counts` snapshot) — a set difference over names would
    miss reruns of the same spans, counts do not."""
    with _mu:
        return {k for k, n in _seen_n.items()
                if n > int(baseline.get(k, 0))}


def reset() -> None:
    """Clear the seen-set and the phase-time table (test isolation)."""
    with _mu:
        _seen.clear()
        _seen_n.clear()
        _phase_s.clear()
        _phase_n.clear()


def resolve_trace_mode(mode) -> str:
    """Validate ``tpu_trace_mode``; unknown values warn and fall back to
    ``full`` (the pre-knob behavior of ``tpu_trace_dir``)."""
    m = str(mode or "full").strip().lower() or "full"
    if m not in _TRACE_MODES:
        from ..utils import log
        log.warning(f"unrecognized tpu_trace_mode={mode!r} "
                    f"(one of {_TRACE_MODES}); using 'full'")
        return "full"
    return m


@contextlib.contextmanager
def trace_session(trace_dir: Optional[str] = None,
                  mode: str = "full") -> Iterator[None]:
    """One telemetry session: spans enabled for the block, and (in
    ``full`` mode with a directory) a ``jax.profiler.trace`` written to
    ``trace_dir``.

    This is the ``tpu_trace_dir`` context engine.train holds around the
    WHOLE training loop — as a context manager, so the profiler trace is
    closed on every error path (the raw ``__enter__``-then-``finally``
    wiring it replaces leaked the trace if setup raised before the try).
    ``mode="annotations"`` enables span names (device-trace metadata +
    the host phase table) without paying for a full profiler trace.
    """
    mode = resolve_trace_mode(mode)
    profiler = None
    enable_annotations()
    try:
        if trace_dir and mode == "full":
            profiler = jax.profiler.trace(str(trace_dir))
            profiler.__enter__()
        try:
            yield
        finally:
            if profiler is not None:
                profiler.__exit__(None, None, None)
    finally:
        disable_annotations()
